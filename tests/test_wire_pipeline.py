"""Zero-copy wire codec properties + stage-overlapped commit pipeline.

The PR-6 perf seams: (1) the reusable WriteBuffer encode path and
memoryview decode path must roundtrip arbitrary registered messages —
including buffer reuse, growth from tiny capacities and frames arriving
in dribbled partial reads; (2) the wire ProxyPipeline must OVERLAP
batch N+1's resolution with batch N's tlog push (ordering enforced
only at the Notified-chain handoffs) while client replies stay
version-ordered; (3) the read coalescer and the batched applier must
preserve exact MVCC semantics.

The r12 columnar seam: the ResolveBatchColumnar frame must roundtrip
byte-for-byte against the object-path packer (columnar decode ->
pack_batch_columnar must equal pack_batch EXACTLY), reject truncated /
corrupt / internally-inconsistent frames with CodecError (never a
crash), survive dribbled partial reads, and produce the same resolver
decisions as the object frame on real ResolverRole backends.
"""

import asyncio
import dataclasses
import random
import struct

import numpy as np
import pytest

from foundationdb_tpu.cluster import multiprocess as mp
from foundationdb_tpu.models.types import (
    CommitTransaction,
    ResolveTransactionBatchReply,
    ResolveTransactionBatchRequest,
    TransactionResult,
)
from foundationdb_tpu.utils import packing
from foundationdb_tpu.wire import codec, transport
from foundationdb_tpu.wire.codec import Mutation

# ---------------------------------------------------------------------------
# Codec property tests (seeded random — no external property library).


def _rand_bytes(rng, lo=0, hi=64):
    return bytes(rng.getrandbits(8) for _ in range(rng.randint(lo, hi)))


def _rand_txn(rng):
    def ranges():
        out = []
        for _ in range(rng.randint(0, 5)):
            b = _rand_bytes(rng, 1, 24)
            out.append((b, b + b"\x00" + _rand_bytes(rng, 0, 4)))
        return out

    return CommitTransaction(
        read_conflict_ranges=ranges(),
        write_conflict_ranges=ranges(),
        read_snapshot=rng.randint(0, 2**50),
        report_conflicting_keys=bool(rng.getrandbits(1)),
        mutations=[
            Mutation(rng.randint(0, 1), _rand_bytes(rng, 1, 32),
                     _rand_bytes(rng, 0, 128))
            for _ in range(rng.randint(0, 6))
        ],
    )


def _rand_columnar(rng):
    txns = [_rand_txn(rng) for _ in range(rng.randint(0, 6))]
    for t in txns:
        t.mutations = []  # the columnar frame carries conflict metadata only
    return codec.ResolveBatchColumnar(
        prev_version=rng.randint(-1, 100),
        version=rng.randint(100, 2**40),
        last_received_version=rng.randint(-1, 100),
        cols=packing.pack_columnar(txns),
        debug_id=None if rng.getrandbits(1) else f"d{rng.randint(0, 99)}",
        span=None if rng.getrandbits(1) else (rng.randint(1, 2**60), 7),
    )


def _rand_messages(seed, n=60):
    rng = random.Random(seed)
    msgs = []
    for _ in range(n):
        pick = rng.randint(0, 6)
        if pick == 6:
            msgs.append(_rand_columnar(rng))
        elif pick == 0:
            msgs.append(_rand_txn(rng))
        elif pick == 1:
            msgs.append(ResolveTransactionBatchRequest(
                prev_version=rng.randint(-1, 100),
                version=rng.randint(100, 2**40),
                last_received_version=rng.randint(-1, 100),
                transactions=[_rand_txn(rng) for _ in range(rng.randint(0, 4))],
            ))
        elif pick == 2:
            msgs.append(ResolveTransactionBatchReply(
                committed=[rng.randint(0, 2) for _ in range(rng.randint(0, 8))]
            ))
        elif pick == 3:
            msgs.append(mp.StorageGetBatch(
                versions=[rng.randint(0, 2**40)
                          for _ in range(rng.randint(0, 10))],
                keys=[_rand_bytes(rng, 1, 40)
                      for _ in range(rng.randint(0, 10))],
            ))
        elif pick == 4:
            msgs.append(mp.StorageGetBatchReply(values=[
                None if rng.getrandbits(1) else _rand_bytes(rng, 0, 64)
                for _ in range(rng.randint(0, 10))
            ]))
        else:
            n_v = rng.randint(0, 5)
            msgs.append(mp.StorageApplyBatch(
                versions=[rng.randint(0, 2**40) for _ in range(n_v)],
                groups=[
                    [Mutation(0, _rand_bytes(rng, 1, 16),
                              _rand_bytes(rng, 0, 32))
                     for _ in range(rng.randint(0, 3))]
                    for _ in range(n_v)
                ],
            ))
    return msgs


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_codec_random_roundtrip_property(seed):
    for msg in _rand_messages(seed):
        got = codec.decode(codec.encode(msg))
        assert got == msg, (msg, got)


@pytest.mark.parametrize("seed", [7, 8])
def test_codec_reused_buffer_matches_fresh_encode(seed):
    """One WriteBuffer reused across every message (the steady-state
    transport discipline) must produce bytes identical to a fresh
    per-message encode, and earlier getvalue() results must survive
    later reuse (they are copies, not views)."""
    buf = codec.WriteBuffer(capacity=16)  # forces growth paths
    snapshots = []
    msgs = _rand_messages(seed, n=40)
    for msg in msgs:
        buf.reset()
        codec.encode_into(buf, msg)
        snapshots.append(buf.getvalue())
    for msg, snap in zip(msgs, snapshots):
        assert snap == codec.encode(msg)
        assert codec.decode(snap) == msg


def test_codec_decode_from_offset_memoryview():
    """decode must accept a payload that sits at a nonzero offset of a
    larger buffer (the transport's frame slices) without copying."""
    msg = _rand_txn(random.Random(42))
    payload = codec.encode(msg)
    framed = b"\xaa" * 7 + payload + b"\xbb" * 3
    view = memoryview(framed)[7 : 7 + len(payload)]
    assert codec.decode(view) == msg


def test_write_buffer_reserve_patch():
    buf = codec.WriteBuffer(capacity=8)
    off = buf.reserve(8)
    buf.put_bytes(b"hello world, this grows past capacity")
    buf.patch_u32(off, 0xDEADBEEF)
    buf.patch_u32(off + 4, len(buf) - 8)
    raw = buf.getvalue()
    a, b = struct.unpack_from("<II", raw, 0)
    assert a == 0xDEADBEEF and b == len(raw) - 8


def _drain_writer():
    class W:
        def __init__(self):
            self.chunks = []

        def write(self, b):
            # a transport consumes the view synchronously; copy like a
            # real socket would before the buffer is reused
            self.chunks.append(bytes(b))

    return W()


@pytest.mark.parametrize("chunk_size", [1, 3, 7, 1024])
@pytest.mark.parametrize("maker", [_rand_txn, _rand_columnar])
def test_frame_roundtrip_partial_reads(chunk_size, maker):
    """A _FrameBuffer-framed message fed to the reader in dribbled
    chunks (rolled/partial reads) must reassemble and decode exactly —
    the columnar frame included (its decoder reads zero-copy views of
    the reassembled payload); a corrupted byte must fail the CRC
    check."""

    async def go():
        fb = transport._FrameBuffer(zero_copy=True)
        w = _drain_writer()
        msg = maker(random.Random(chunk_size))
        preamble = transport._REQ.pack(transport.KIND_REQUEST, 77, 0x0101)
        fb.send(w, preamble, msg=msg)
        wire_bytes = b"".join(w.chunks)

        reader = asyncio.StreamReader()
        for i in range(0, len(wire_bytes), chunk_size):
            reader.feed_data(wire_bytes[i : i + chunk_size])
        body = await transport._read_frame(reader)
        kind, reqid, token = transport._REQ.unpack_from(body, 0)
        assert (kind, reqid, token) == (transport.KIND_REQUEST, 77, 0x0101)
        assert codec.decode(body[transport._REQ.size :]) == msg

        # flip one payload byte -> checksum failure
        corrupted = bytearray(wire_bytes)
        corrupted[-1] ^= 0xFF
        reader2 = asyncio.StreamReader()
        reader2.feed_data(bytes(corrupted))
        with pytest.raises(transport.ChecksumError):
            await transport._read_frame(reader2)

    asyncio.run(go())


def test_frame_buffer_reuse_across_messages():
    """Consecutive sends through one _FrameBuffer (the per-connection
    steady state) must each produce an independently valid frame."""

    async def go():
        fb = transport._FrameBuffer(zero_copy=True)
        w = _drain_writer()
        msgs = _rand_messages(11, n=10)
        frames = []
        for i, m in enumerate(msgs):
            before = len(w.chunks)
            fb.send(w, transport._REQ.pack(transport.KIND_REQUEST, i, 1),
                    msg=m)
            frames.append(b"".join(w.chunks[before:]))
        for i, (m, f) in enumerate(zip(msgs, frames)):
            reader = asyncio.StreamReader()
            reader.feed_data(f)
            body = await transport._read_frame(reader)
            kind, reqid, _token = transport._REQ.unpack_from(body, 0)
            assert reqid == i
            assert codec.decode(body[transport._REQ.size :]) == m

    asyncio.run(go())


# ---------------------------------------------------------------------------
# Stage-overlapped pipeline: stub roles with controllable latencies.


class _StubConn:
    """Duck-typed RpcConnection: in-process handlers + event journal."""

    def __init__(self, journal, latencies=None):
        self.journal = journal
        self.latencies = latencies or {}

    async def call(self, token, msg, **_kw):
        raise NotImplementedError


def _req_txn_count(req) -> int:
    """Batch size of either resolve frame (object or columnar)."""
    if isinstance(req, codec.ResolveBatchColumnar):
        return req.cols.n_txns
    return len(req.transactions)


class _StubResolver(_StubConn):
    def __init__(self, journal, latency=0.0):
        super().__init__(journal)
        self.latency = latency
        self.version = -1
        self.frames: list[type] = []  # frame types received, in order

    async def call(self, token, req, **_kw):
        assert token == mp.TOKEN_RESOLVE
        self.frames.append(type(req))
        self.journal.append(("resolve_start", req.version))
        if self.latency:
            await asyncio.sleep(self.latency)
        # version-chain contract (Resolver.actor.cpp): requests arrive
        # with prev_version == our current version when pipelined
        # in-order from one proxy
        assert req.prev_version >= self.version or self.version == -1
        self.version = req.version
        self.journal.append(("resolve_end", req.version))
        return ResolveTransactionBatchReply(
            committed=[int(TransactionResult.COMMITTED)]
            * _req_txn_count(req)
        )


class _StubTLog(_StubConn):
    def __init__(self, journal, latency=0.0):
        super().__init__(journal)
        self.latency = latency
        self.version = -1

    async def call(self, token, req, **_kw):
        assert token == mp.TOKEN_TLOG_PUSH
        self.journal.append(("push_start", req.version))
        if self.latency:
            await asyncio.sleep(self.latency)
        assert req.version > self.version
        self.version = req.version
        self.journal.append(("push_end", req.version))
        return mp.TLogPushReply(durable_version=self.version)


class _StubStorage(_StubConn):
    def __init__(self, journal):
        super().__init__(journal)
        self.version = 0
        self.data = {}

    async def call(self, token, req, **_kw):
        if token == mp.TOKEN_STORAGE_APPLY_BATCH:
            self.journal.append(("apply_batch", tuple(req.versions)))
            assert list(req.versions) == sorted(req.versions)
            for v, muts in zip(req.versions, req.groups):
                assert v > self.version
                for m in muts:
                    self.data.setdefault(m.param1, []).append((v, m.param2))
                self.version = v
            return mp.StorageApplyReply(durable_version=self.version)
        if token == mp.TOKEN_STORAGE_GET_BATCH:
            self.journal.append(("get_batch", tuple(req.keys)))
            vals = []
            for k, rv in zip(req.keys, req.versions):
                assert self.version >= rv, "read served before apply"
                val = None
                for v, x in self.data.get(k, []):
                    if v <= rv:
                        val = x
                vals.append(val)
            return mp.StorageGetBatchReply(values=vals)
        raise AssertionError(f"unexpected token {token:#x}")


def _txn(key: bytes, value: bytes, rv: int = 0) -> CommitTransaction:
    kr = (key, key + b"\x00")
    return CommitTransaction(
        read_conflict_ranges=[kr], write_conflict_ranges=[kr],
        read_snapshot=rv, mutations=[Mutation(0, key, value)],
    )


def test_batch_overlap_resolve_vs_log_push_and_ordered_replies():
    """THE pipelining pin: with a slow tlog, batch N+1's resolve must
    START (and finish) while batch N's push is still in flight —
    overlap enforced only at the Notified-chain handoff — and the
    client replies must still complete in version order."""

    async def go():
        journal = []
        resolver = _StubResolver(journal, latency=0.0)
        tlog = _StubTLog(journal, latency=0.05)
        storage = _StubStorage(journal)
        pipe = mp.ProxyPipeline(
            [resolver], tlog, storage,
            batch_interval=0.005, max_batch=4,
        )
        pipe.start()
        reply_order = []

        async def commit(key, tag):
            v = await pipe.commit(_txn(key, b"v-" + tag))
            reply_order.append((tag, v))
            return v

        # wave 1 -> batch 1; wave 2 lands while batch 1's push sleeps
        t1 = asyncio.ensure_future(commit(b"k1", b"a"))
        await asyncio.sleep(0.02)  # batch 1 dispatched, push in flight
        t2 = asyncio.ensure_future(commit(b"k2", b"b"))
        v1, v2 = await t1, await t2
        await pipe.stop()

        assert v2 > v1
        # journal proves the overlap: batch 2's resolve_end lands
        # between batch 1's push_start and push_end
        def idx(ev):
            return journal.index(ev)

        assert idx(("resolve_end", v2)) < idx(("push_end", v1)), journal
        assert idx(("push_start", v1)) < idx(("resolve_start", v2)), journal
        # pushes themselves stay strictly ordered by the chain
        assert idx(("push_end", v1)) < idx(("push_start", v2)), journal
        # replies completed in version order
        assert reply_order == [(b"a", v1), (b"b", v2)]
        # applies arrived version-ordered and batched
        applied = [v for ev, vs in journal if ev == "apply_batch"
                   for v in vs]
        assert applied == sorted(applied) and set(applied) == {v1, v2}

    asyncio.run(go())


def test_read_coalescer_single_rpc_exact_versions():
    """Reads issued in the same event-loop turn ride ONE StorageGetBatch
    and each key is served at ITS version (not the batch max)."""

    async def go():
        journal = []
        resolver = _StubResolver(journal)
        tlog = _StubTLog(journal)
        storage = _StubStorage(journal)
        pipe = mp.ProxyPipeline(
            [resolver], tlog, storage, batch_interval=0.002, max_batch=64,
        )
        pipe.start()
        v1 = await pipe.commit(_txn(b"k", b"old"))
        # ensure the apply drained so v1 is readable
        while storage.version < v1:
            await asyncio.sleep(0.002)
        v2 = await pipe.commit(_txn(b"k", b"new"))
        while storage.version < v2:
            await asyncio.sleep(0.002)

        journal.clear()
        r_old, r_new = await asyncio.gather(
            pipe.read(b"k", v1), pipe.read(b"k", v2)
        )
        await pipe.stop()
        assert r_old == b"old" and r_new == b"new"
        gets = [ev for ev in journal if ev[0] == "get_batch"]
        assert len(gets) == 1 and len(gets[0][1]) == 2, journal

    asyncio.run(go())


def test_successor_failure_does_not_fail_inflight_predecessor():
    """A FAILED batch N advances the logging chain past a still-pushing
    batch N-1 (fail-fast for N's successors). N-1's durable commit must
    survive that leapfrog: its clients get their version, its storage
    apply is enqueued — never a Notified-must-not-decrease error
    converting a committed batch into a client failure."""

    class _SecondBatchDiesResolver(_StubResolver):
        def __init__(self, journal):
            super().__init__(journal)
            self.calls = 0

        async def call(self, token, req, **_kw):
            self.calls += 1
            if self.calls >= 2:
                raise transport.RemoteError("resolver died")
            return await super().call(token, req, **_kw)

    class _GatedTLog(_StubTLog):
        """Push completes only when the test releases it — pins the
        interleaving deterministically (no real-time races)."""

        def __init__(self, journal, release):
            super().__init__(journal)
            self.release = release

        async def call(self, token, req, **_kw):
            assert token == mp.TOKEN_TLOG_PUSH
            self.journal.append(("push_start", req.version))
            await self.release.wait()
            assert req.version > self.version
            self.version = req.version
            self.journal.append(("push_end", req.version))
            return mp.TLogPushReply(durable_version=self.version)

    async def go():
        journal = []
        release = asyncio.Event()
        resolver = _SecondBatchDiesResolver(journal)
        tlog = _GatedTLog(journal, release)
        storage = _StubStorage(journal)
        pipe = mp.ProxyPipeline(
            [resolver], tlog, storage, batch_interval=0.005, max_batch=4,
        )
        pipe.start()
        t1 = asyncio.ensure_future(pipe.commit(_txn(b"k1", b"v1")))
        while not any(ev[0] == "push_start" for ev in journal):
            await asyncio.sleep(0.001)  # batch 1's push now in flight
        t2 = asyncio.ensure_future(pipe.commit(_txn(b"k2", b"v2")))
        # batch 2's resolve dies while batch 1's push is HELD: its
        # error path advances the logging chain past batch 1
        with pytest.raises(transport.RemoteError):
            await t2
        assert pipe.failed is not None
        release.set()  # batch 1's push becomes durable AFTER the leapfrog
        v1 = await t1  # batch 1 committed despite the leapfrog
        # batch 1's apply was enqueued and drains to storage
        for _ in range(200):
            if storage.version >= v1:
                break
            await asyncio.sleep(0.005)
        assert storage.version >= v1, "committed batch's apply dropped"
        await pipe.stop()

    asyncio.run(go())


# ---------------------------------------------------------------------------
# Columnar resolve frame (r12).


def _rand_small_txns(rng, n_max=12):
    """Random txns with snapshots inside int32-offset range (so the
    kernel packer can run) and no mutations (the stripped hop)."""
    txns = []
    for _ in range(rng.randint(0, n_max)):
        t = _rand_txn(rng)
        t.mutations = []
        t.read_snapshot = rng.randint(0, 2**30)
        txns.append(t)
    return txns


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_columnar_decode_equals_pack_batch_byte_for_byte(seed):
    """THE columnar contract: encode the frame, decode it over an
    offset memoryview (the transport shape), run pack_batch_columnar on
    the decoded columns — every PackedBatch field must equal the
    object path's pack_batch output EXACTLY, dtypes included."""
    from foundationdb_tpu.config import KernelConfig

    rng = random.Random(seed)
    cfg = KernelConfig(
        max_key_bytes=16, max_txns=16, max_reads=128, max_writes=128,
        history_capacity=512, window_versions=1000,
    )
    for trial in range(20):
        txns = _rand_small_txns(rng)
        msg = codec.ResolveBatchColumnar(
            prev_version=-1, version=100 + trial,
            last_received_version=-1, cols=packing.pack_columnar(txns),
        )
        payload = codec.encode(msg)
        framed = b"\xaa" * 5 + payload + b"\xbb" * 3
        dec = codec.decode(memoryview(framed)[5 : 5 + len(payload)])
        assert dec == msg
        a = packing.pack_batch(txns, 100 + trial, 0, cfg)
        b = packing.pack_batch_columnar(dec.cols, 100 + trial, 0, cfg)
        for f in dataclasses.fields(a):
            va, vb = getattr(a, f.name), getattr(b, f.name)
            if isinstance(va, np.ndarray):
                assert va.dtype == vb.dtype and np.array_equal(va, vb), (
                    trial, f.name,
                )
            else:
                assert va == vb, (trial, f.name)
        # and the object fallback reconstructs EXACT transactions
        for t0, t1 in zip(txns, packing.columnar_to_transactions(dec.cols)):
            assert t0.read_conflict_ranges == t1.read_conflict_ranges
            assert t0.write_conflict_ranges == t1.write_conflict_ranges
            assert t0.read_snapshot == t1.read_snapshot
            assert t0.report_conflicting_keys == t1.report_conflicting_keys


def test_columnar_truncation_always_codec_error():
    """Every truncation point of a columnar frame must raise CodecError
    — never struct.error, IndexError or a numpy exception a role
    handler wouldn't have promised to contain."""
    rng = random.Random(31)
    msg = _rand_columnar(rng)
    raw = codec.encode(msg)
    assert codec.decode(raw) == msg
    for cut in range(0, len(raw) - 1):
        with pytest.raises(codec.CodecError):
            codec.decode(raw[:cut])


def test_columnar_inconsistent_frames_rejected():
    """Fuzz the frame's internal consistency: header counts that don't
    match the column sums, key lengths that don't tile the blob, and
    trailing garbage must ALL reject with CodecError (the decoder's
    offsets are cumsum-derived, so these checks are what makes an
    out-of-bounds slice unrepresentable)."""
    rng = random.Random(32)
    txns = _rand_small_txns(rng, n_max=8) or _rand_small_txns(
        random.Random(33), n_max=8
    )
    while not txns:
        txns = _rand_small_txns(rng, n_max=8)
    msg = codec.ResolveBatchColumnar(
        prev_version=-1, version=100, last_received_version=-1,
        cols=packing.pack_columnar(txns),
    )
    raw = bytearray(codec.encode(msg))
    # payload layout: u16 type id, 4*i64 header (prev/version/last/
    # epoch — epoch since protocol 0008), then n_txns/n_reads/n_writes
    # as u32 at these offsets
    off_ntxns, off_nreads, off_nwrites = 34, 38, 42
    for off, delta in [
        (off_ntxns, 1), (off_ntxns, -1),
        (off_nreads, 1), (off_nreads, -1),
        (off_nwrites, 1), (off_nwrites, 7),
    ]:
        bad = bytearray(raw)
        v = struct.unpack_from("<I", bad, off)[0] + delta
        if v < 0:
            continue
        struct.pack_into("<I", bad, off, v)
        with pytest.raises(codec.CodecError):
            codec.decode(bytes(bad))
    # corrupt the blob length prefix (sum(key_lens) check) — find it by
    # re-encoding with a poisoned blob length via direct byte surgery:
    # the key_lens sum check must reject a blob one byte short/long
    if msg.cols.n_reads + msg.cols.n_writes:
        # locate the u32 blob length: it precedes the blob, which is
        # the only place the blob bytes appear; easier to just flip a
        # key_lens entry (first key_lens array byte after the flags)
        n = msg.cols.n_txns
        off_lens = 46 + 8 * n + 4 * n + 4 * n + n  # first key_lens entry
        bad = bytearray(raw)
        v = struct.unpack_from("<I", bad, off_lens)[0]
        struct.pack_into("<I", bad, off_lens, v + 1)
        with pytest.raises(codec.CodecError):
            codec.decode(bytes(bad))
    # trailing garbage after a well-formed frame
    with pytest.raises(codec.CodecError):
        codec.decode(bytes(raw) + b"\x00")


def test_corrupt_columnar_frame_does_not_crash_role():
    """End to end over a real RpcServer: a corrupted columnar payload
    comes back as an error frame (RemoteError), and the SAME connection
    then serves a valid request — the role survives."""

    async def go(tmp_path):
        served = []

        async def resolve(req):
            served.append(req)
            return ResolveTransactionBatchReply(
                committed=[int(TransactionResult.COMMITTED)]
                * _req_txn_count(req)
            )

        addr = str(tmp_path / "res.sock")
        server = transport.RpcServer(addr)
        server.register(mp.TOKEN_RESOLVE, resolve)
        await server.start()
        try:
            conn = transport.RpcConnection(addr)
            await conn.connect()
            txns = _rand_small_txns(random.Random(5)) or []
            msg = codec.ResolveBatchColumnar(
                prev_version=-1, version=10, last_received_version=-1,
                cols=packing.pack_columnar(txns),
            )
            # corrupt the n_reads header count and ship the raw payload
            payload = bytearray(codec.encode(msg))
            struct.pack_into(
                "<I", payload, 38,
                struct.unpack_from("<I", payload, 38)[0] + 3,
            )
            reqid = conn._next_id
            conn._next_id += 1
            fut = asyncio.get_running_loop().create_future()
            conn._waiters[reqid] = fut
            conn._fb.send(
                conn._writer,
                transport._REQ.pack(
                    transport.KIND_REQUEST, reqid, mp.TOKEN_RESOLVE
                ),
                raw=bytes(payload),
            )
            await conn._writer.drain()
            with pytest.raises(transport.RemoteError, match="columnar"):
                await fut
            assert not served  # the corrupt frame never reached the handler
            # the connection (and role) still serve a valid request
            rep = await conn.call(mp.TOKEN_RESOLVE, msg)
            assert len(rep.committed) == msg.cols.n_txns
            assert len(served) == 1
            await conn.close()
        finally:
            await server.close()

    import tempfile
    from pathlib import Path

    with tempfile.TemporaryDirectory() as d:
        asyncio.run(go(Path(d)))


@pytest.mark.parametrize("backend", ["native", "cpu"])
def test_resolver_role_columnar_object_decision_parity(backend):
    """The same batches through a real ResolverRole twice — once as
    object frames, once as columnar — must produce identical verdicts
    AND identical conflicting-key reports, on both the native skip list
    (object fallback via columnar_to_transactions) and the CPU oracle."""

    async def go():
        rng = random.Random(77)
        role_obj = mp.ResolverRole(backend=backend)
        role_col = mp.ResolverRole(backend=backend)
        prev = -1
        for i in range(6):
            version = (i + 1) * 100
            txns = _rand_small_txns(rng)
            obj_req = ResolveTransactionBatchRequest(
                prev_version=prev, version=version,
                last_received_version=prev, transactions=txns,
            )
            col_req = codec.ResolveBatchColumnar(
                prev_version=prev, version=version,
                last_received_version=prev,
                cols=packing.pack_columnar(txns),
            )
            # wire-roundtrip the columnar frame for full fidelity
            col_req = codec.decode(codec.encode(col_req))
            a = await role_obj.resolve(obj_req)
            b = await role_col.resolve(col_req)
            assert [int(v) for v in a.committed] == [
                int(v) for v in b.committed
            ], (i, a.committed, b.committed)
            assert a.conflicting_key_range_map == b.conflicting_key_range_map
            prev = version
        # structural accounting took the expected paths
        assert role_obj.path_stats["object_batches"] == 6
        assert role_col.path_stats["columnar_batches"] == 6
        # object-consuming backends pay ONE copy per batch either way
        assert role_obj.path_stats["copies"] == 6
        assert role_col.path_stats["copies"] == 6

    asyncio.run(go())


def test_pipeline_columnar_frame_selection_and_escape_hatch():
    """ProxyPipeline(resolve_columnar=True) ships ResolveBatchColumnar;
    =False (the RESOLVE_COLUMNAR=0 escape hatch) ships the object
    frame; commits succeed identically through both."""

    async def go(columnar):
        journal = []
        resolver = _StubResolver(journal)
        pipe = mp.ProxyPipeline(
            [resolver], _StubTLog(journal), _StubStorage(journal),
            batch_interval=0.002, max_batch=8,
            resolve_columnar=columnar,
        )
        pipe.start()
        v = await pipe.commit(_txn(b"k", b"v"))
        await pipe.stop()
        assert v > 0
        want = (
            codec.ResolveBatchColumnar if columnar
            else ResolveTransactionBatchRequest
        )
        assert resolver.frames == [want]

    asyncio.run(go(True))
    asyncio.run(go(False))


def test_pipeline_failure_fails_fast_not_wedged():
    """A mid-chain resolver death must fail that batch's clients AND
    every later commit immediately (failed-generation discipline), not
    wedge successors on when_at_least forever."""

    class _DyingResolver(_StubResolver):
        async def call(self, token, req, **_kw):
            raise transport.RemoteError("resolver died")

    async def go():
        journal = []
        pipe = mp.ProxyPipeline(
            [_DyingResolver(journal)], _StubTLog(journal),
            _StubStorage(journal), batch_interval=0.002, max_batch=4,
        )
        pipe.start()
        with pytest.raises(transport.RemoteError):
            await pipe.commit(_txn(b"k", b"v"))
        assert pipe.failed is not None
        with pytest.raises(transport.RemoteError):
            await asyncio.wait_for(pipe.commit(_txn(b"k", b"v2")), 1.0)
        await pipe.stop()

    asyncio.run(go())
