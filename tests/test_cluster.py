"""End-to-end cluster tests: client txns through GRV -> proxy -> resolver
(TPU kernel) -> tlog -> storage -> reads.

Test bodies mirror the reference's workload style (SURVEY.md §4):
correctness invariants checked against the live system, with the Cycle
workload's invariant as the serializability probe
(fdbserver/workloads/Cycle.actor.cpp: disjoint pointer-swap transactions
must preserve a single N-cycle through the keyspace).
"""

import pytest

from foundationdb_tpu.cluster.commit_proxy import NotCommitted
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


@pytest.fixture(scope="module")
def world():
    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=2, n_resolvers=2, n_storage=2)
    )
    yield sched, cluster, db
    cluster.stop()


def test_set_get_roundtrip(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        txn.set(b"hello", b"world")
        txn.set(b"\xf0zzz", b"far-shard")  # lands on the other storage shard
        await txn.commit()

        txn2 = db.create_transaction()
        v1 = await txn2.get(b"hello")
        v2 = await txn2.get(b"\xf0zzz")
        missing = await txn2.get(b"nope")
        return v1, v2, missing

    assert run(sched, body()) == (b"world", b"far-shard", None)


def test_read_your_writes(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        txn.set(b"ryw", b"BEFORE")
        await txn.commit()

        txn = db.create_transaction()
        assert await txn.get(b"ryw") == b"BEFORE"
        txn.set(b"ryw", b"AFTER")
        assert await txn.get(b"ryw") == b"AFTER"  # sees own write
        txn.clear(b"ryw")
        assert await txn.get(b"ryw") is None      # sees own clear
        await txn.commit()

        txn = db.create_transaction()
        return await txn.get(b"ryw")

    assert run(sched, body()) is None


def test_conflicting_writers_one_aborts(world):
    sched, cluster, db = world

    async def body():
        init = db.create_transaction()
        init.set(b"ctr", b"0")
        await init.commit()

        # Two read-modify-write txns on the same key, interleaved: both
        # read before either commits -> exactly one must conflict.
        t1 = db.create_transaction()
        t2 = db.create_transaction()
        v1 = await t1.get(b"ctr")
        v2 = await t2.get(b"ctr")
        t1.set(b"ctr", str(int(v1) + 1).encode())
        t2.set(b"ctr", str(int(v2) + 1).encode())
        await t1.commit()
        try:
            await t2.commit()
            return "both committed"
        except NotCommitted:
            return "second aborted"

    assert run(sched, body()) == "second aborted"


def test_range_reads_and_clears(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        for i in range(10):
            txn.set(b"r%03d" % i, b"v%d" % i)
        await txn.commit()

        txn = db.create_transaction()
        items = await txn.get_range(b"r000", b"r005")
        txn.clear_range(b"r002", b"r008")
        after = await txn.get_range(b"r000", b"r010")
        await txn.commit()

        txn = db.create_transaction()
        persisted = await txn.get_range(b"r", b"s")
        return items, after, persisted

    items, after, persisted = run(sched, body())
    assert [k for k, _ in items] == [b"r%03d" % i for i in range(5)]
    assert [k for k, _ in after] == [b"r000", b"r001", b"r008", b"r009"]
    assert persisted == after


def test_snapshot_read_no_conflict(world):
    sched, cluster, db = world

    async def body():
        init = db.create_transaction()
        init.set(b"snap", b"0")
        await init.commit()

        t1 = db.create_transaction()
        await t1.get(b"snap", snapshot=True)  # snapshot read: no conflict range
        t2 = db.create_transaction()
        t2.set(b"snap", b"1")
        await t2.commit()
        t1.set(b"other", b"x")
        await t1.commit()  # must succeed despite the concurrent write
        return True

    assert run(sched, body())


def test_cycle_workload_invariant(world):
    """The Cycle workload: keys 0..N-1 form a permutation cycle; each txn
    rotates three pointers; serializability must preserve one N-cycle."""
    sched, cluster, db = world
    n = 8

    def key(i):
        return b"cycle/%02d" % i

    async def setup():
        txn = db.create_transaction()
        for i in range(n):
            txn.set(key(i), str((i + 1) % n).encode())
        await txn.commit()

    async def swap(txn):
        import random

        r = random.Random(sched.now())
        a = r.randrange(n)
        b = int(await txn.get(key(a)))
        c = int(await txn.get(key(b)))
        d = int(await txn.get(key(c)))
        txn.set(key(a), str(c).encode())
        txn.set(key(b), str(d).encode())
        txn.set(key(c), str(b).encode())

    async def body():
        await setup()
        # concurrent swappers via the retry loop
        tasks = [
            sched.spawn(db.run(swap)) for _ in range(12)
        ]
        from foundationdb_tpu.runtime.flow import all_of

        await all_of([t.done for t in tasks])
        txn = db.create_transaction()
        ptrs = [int(await txn.get(key(i))) for i in range(n)]
        return ptrs

    ptrs = run(sched, body())
    seen = set()
    at = 0
    for _ in range(n):
        assert at not in seen
        seen.add(at)
        at = ptrs[at]
    assert at == 0 and len(seen) == n


def test_special_key_space_modules():
    """SpecialKeySpace surface: worker inventory, resolver metrics,
    coordinators, DD key counts (SpecialKeySpace.actor.cpp modules)."""
    import json

    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=1, n_resolvers=1, n_storage=2)
    )

    async def go():
        t = db.create_transaction()
        t.set(b"k", b"v")
        await t.commit()
        t = db.create_transaction()
        w = json.loads(await t.get(b"\xff\xff/worker_interfaces"))
        assert w["resolvers"] and w["storage"] and w["coordinators"]
        m = json.loads(await t.get(b"\xff\xff/metrics/resolver"))
        assert m[0]["resolveBatchIn"] > 0
        c = json.loads(await t.get(b"\xff\xff/coordinators"))
        assert c["alive"] == c["total"] == 3 and c["quorum"] == 2
        kc = json.loads(await t.get(b"\xff\xff/data_distribution/key_counts"))
        assert isinstance(kc, list)
        assert await t.get(b"\xff\xff/definitely/not/a/module") is None
        return True

    task = sched.spawn(go(), name="drive")
    sched.run_until(task.done)
    assert task.done.get()
    cluster.stop()
