"""Replication tests: storage teams, replica failover, team repair.

The reference replicates each shard across a storage team (mutations
tagged to every member, reads load-balanced across them, teams repaired
by DataDistribution after failures). These tests pin that behavior for
the teamed ShardMap.
"""

import pytest

from foundationdb_tpu.cluster.consistency import check_cluster
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


@pytest.fixture
def world():
    sched, cluster, db = open_cluster(
        ClusterConfig(n_storage=3, replication_factor=2)
    )
    yield sched, cluster, db
    cluster.stop()


def test_mutations_reach_every_replica(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        for i in range(12):
            txn.set(b"rep%02d" % i, b"v%d" % i)
        await txn.commit()
        await sched.delay(0.05)

    run(sched, body())
    stats = check_cluster(cluster)
    assert stats["replica_compares"] >= 1
    # every key present on exactly its team's two members
    sm = cluster.key_servers
    for i in range(12):
        k = b"rep%02d" % i
        team = sm.team_of(k)
        assert len(team) == 2
        for s in team:
            assert cluster.storage_servers[s]._data.get(k) == b"v%d" % i
        for s in set(range(3)) - set(team):
            assert k not in cluster.storage_servers[s]._data


def test_reads_survive_replica_failure(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        for i in range(12):
            txn.set(b"rf%02d" % i, b"v%d" % i)
        await txn.commit()

        victim = cluster.key_servers.team_of(b"rf00")[0]
        cluster.kill_storage(victim)

        # every key is still readable (failover to the live replica),
        # and writes still commit (mutations tagged to the dead member
        # simply queue in the log)
        txn = db.create_transaction()
        vals = [await txn.get(b"rf%02d" % i) for i in range(12)]
        txn.set(b"rf00", b"after-failure")
        await txn.commit()
        txn = db.create_transaction()
        return vals, await txn.get(b"rf00")

    vals, after = run(sched, body())
    assert vals == [b"v%d" % i for i in range(12)]
    assert after == b"after-failure"


def test_team_repair_restores_replication(world):
    sched, cluster, db = world
    dd = cluster.data_distributor

    async def body():
        txn = db.create_transaction()
        for i in range(12):
            txn.set(b"tr%02d" % i, b"v%d" % i)
        await txn.commit()

        victim = cluster.key_servers.team_of(b"tr00")[0]
        cluster.kill_storage(victim)
        replacement = next(
            s for s in range(3)
            if s != victim and s not in cluster.key_servers.team_of(b"tr00")
        )
        n = await dd.repair(victim, replacement)
        await sched.delay(0.2)  # deferred drops + catch-up
        return victim, n

    victim, repaired = run(sched, body())
    assert repaired >= 1
    # no team references the dead server anymore
    for _b, _e, team in cluster.key_servers.ranges():
        assert victim not in team
    # and replicas agree again
    stats = check_cluster(cluster)
    assert stats["replica_compares"] >= 1

    async def verify():
        txn = db.create_transaction()
        return await txn.get_range(b"tr", b"ts")

    items = run(sched, verify())
    assert len(items) == 12
