"""MultiVersion client: protocol probing + upgrade hot-swap
(fdbclient/MultiVersionTransaction.actor.cpp capability)."""

import asyncio

import pytest

from foundationdb_tpu.cluster.multiprocess import Ping, Pong
from foundationdb_tpu.cluster.multiversion import (
    ClusterVersionChangedError,
    MultiVersionClient,
)
from foundationdb_tpu.wire import transport

TOKEN = 0x5151
PV_OLD = 0x0FDB_7E50_0004
PV_NEW = 0x0FDB_7E50_0005


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


async def _serve(address, pv):
    server = transport.RpcServer(address, protocol_version=pv)

    async def ping(msg: Ping) -> Pong:
        return Pong(payload=msg.payload + b"@%x" % pv)

    server.register(TOKEN, ping)
    await server.start()
    return server


def test_probes_down_to_older_cluster(tmp_path):
    """A client shipping [new, old] connects to an OLD cluster by
    probing down — the multi-version external-client walk."""
    address = str(tmp_path / "mv.sock")

    async def go():
        server = await _serve(address, PV_OLD)
        mv = MultiVersionClient(address, [PV_NEW, PV_OLD])
        rep = await mv.call(TOKEN, Ping(payload=b"x"))
        assert rep.payload == b"x@%x" % PV_OLD
        assert mv.protocol_version == PV_OLD
        await mv.close()
        await server.close()

    run(go())


def test_upgrade_raises_cluster_version_changed_then_works(tmp_path):
    """Cluster restarts on a NEWER protocol mid-session: the in-flight
    call fails with cluster_version_changed (retryable), and the retry
    runs on the hot-swapped client."""
    import os

    address = str(tmp_path / "mv.sock")

    async def go():
        server = await _serve(address, PV_OLD)
        mv = MultiVersionClient(address, [PV_NEW, PV_OLD])
        rep = await mv.call(TOKEN, Ping(payload=b"a"))
        assert mv.protocol_version == PV_OLD

        # the upgrade: old server gone, new one at PV_NEW
        await server.close()
        os.unlink(address)
        server2 = await _serve(address, PV_NEW)
        with pytest.raises(ClusterVersionChangedError):
            await mv.call(TOKEN, Ping(payload=b"b"))
        assert mv.swaps == 1
        # the retry loop's next attempt succeeds on the new client
        rep = await mv.call(TOKEN, Ping(payload=b"c"))
        assert rep.payload == b"c@%x" % PV_NEW
        assert mv.protocol_version == PV_NEW
        await mv.close()
        await server2.close()

    run(go())


def test_same_version_restart_is_at_most_once(tmp_path):
    """A crash/restart at the SAME protocol is NOT a version change —
    but the lost call must RAISE (the request may have executed;
    silently re-sending would double-apply non-idempotent work). The
    client reconnects underneath, so the caller's retry succeeds."""
    import os

    address = str(tmp_path / "mv.sock")

    async def go():
        server = await _serve(address, PV_NEW)
        mv = MultiVersionClient(address, [PV_NEW, PV_OLD])
        await mv.call(TOKEN, Ping(payload=b"a"))
        await server.close()
        os.unlink(address)
        server2 = await _serve(address, PV_NEW)
        with pytest.raises(transport.TransportError):
            await mv.call(TOKEN, Ping(payload=b"b"))
        assert mv.swaps == 0
        # the caller's retry rides the reconnected client
        rep = await mv.call(TOKEN, Ping(payload=b"b"))
        assert rep.payload == b"b@%x" % PV_NEW
        await mv.close()
        await server2.close()

    run(go())


def test_no_common_version_fails_loudly(tmp_path):
    address = str(tmp_path / "mv.sock")

    async def go():
        server = await _serve(address, 0x0FDB_7E50_0001)
        mv = MultiVersionClient(address, [PV_NEW, PV_OLD])
        with pytest.raises(transport.TransportError, match="protocol"):
            await mv.connect(retries=2, delay=0.01)
        await server.close()

    run(go())
