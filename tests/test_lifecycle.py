"""Wire-cluster lifecycle: cluster controller, worker recruitment, and
generation-bumped recovery (ISSUE 13).

The acceptance surface of the subsystem:
* a ClusterControllerRole recruits a declarative topology onto
  registered WorkerRole processes, a kill -9 of a transaction-path
  worker triggers the cluster/generation.py recovery walk, the
  workload resumes in a strictly newer generation, and a pre-recovery
  snapshot aborts conservatively;
* the wire conservative-abort first batch produces the SAME
  commit/abort decisions as the sim recovery on an identical in-flight
  transaction set (oracle comparison, both resolver backends);
* the wire RatekeeperRole re-resolves its peer list from the
  controller's live topology (the frozen-peer-list bugfix), so a
  re-recruited resolver's occupancy feed rejoins the admission law.
"""

import asyncio
import json
import os
import signal
import time

import pytest

from foundationdb_tpu.cluster import generation as gen
from foundationdb_tpu.cluster import multiprocess as mp
from foundationdb_tpu.models.types import CommitTransaction, TransactionResult
from foundationdb_tpu.wire import transport
from foundationdb_tpu.wire.codec import Mutation


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# Controller + worker recruitment and kill -9 recovery.


def test_controller_recruits_and_recovers_from_kill(tmp_path):
    d = str(tmp_path)
    conf = {
        "resolvers": 1,
        "backend": "native",
        "tlog_data_dir": os.path.join(d, "tlog-data"),
        "storage_data_dir": os.path.join(d, "storage-data"),
        "ratekeeper": False,  # keep the test cluster minimal + fast
    }
    conf_path = os.path.join(d, "cluster.json")
    with open(conf_path, "w") as f:
        json.dump(conf, f)
    ctrl = mp.spawn_role("controller", d, cluster_conf=conf_path,
                         state_file=os.path.join(d, "epoch.json"))
    workers = [
        mp.spawn_role("worker", d, index=i, controller=ctrl.address,
                      worker_id=f"w{i}")
        for i in range(5)
    ]
    try:
        async def scenario():
            client = mp.ClusterClient(ctrl.address, recovery_timeout=45)
            await client.connect()
            assert client.epoch >= 1
            epoch0 = client.epoch

            # pre-recovery commits
            for i in range(3):
                rv = await client.get_read_version()
                v = await client.commit(CommitTransaction(
                    write_conflict_ranges=[(b"k%d" % i, b"k%d\x00" % i)],
                    read_snapshot=rv,
                    mutations=[Mutation(0, b"k%d" % i, b"v%d" % i)],
                ))
            assert await client.read(b"k1", v) == b"v1"
            stale_rv = await client.get_read_version()

            # kill -9 the resolver's worker process
            topo = await client.topology()
            res = next(e for e in topo["roles"].values()
                       if e["kind"] == "resolver")
            os.kill(res["pid"], signal.SIGKILL)

            # the controller recovers into a strictly newer generation
            deadline = time.monotonic() + 45
            while time.monotonic() < deadline:
                try:
                    topo = await client.topology()
                    if (topo["epoch"] > epoch0
                            and topo["state"] == gen.FULLY_RECOVERED):
                        break
                except Exception:
                    pass
                await asyncio.sleep(0.2)
            else:
                raise AssertionError(f"no recovery observed: {topo}")
            assert topo["recovery_version"] > v

            # post-recovery: commits flow (ride through unknowns — the
            # client may still hold the fenced generation's connection)
            for _ in range(10):
                try:
                    rv = await client.get_read_version()
                    v2 = await client.commit(CommitTransaction(
                        write_conflict_ranges=[(b"post", b"post\x00")],
                        read_snapshot=rv,
                        mutations=[Mutation(0, b"post", b"yes")],
                    ))
                    break
                except mp.CommitUnknownError:
                    await asyncio.sleep(0.1)
            else:
                raise AssertionError("no post-recovery commit landed")
            # durable data survived the recovery
            assert await client.read(b"k1", v2) == b"v1"
            # conservative abort: pre-recovery snapshot with a read
            # conflict range must NOT commit
            with pytest.raises(mp.NotCommittedError):
                await client.commit(CommitTransaction(
                    read_conflict_ranges=[(b"k0", b"k0\x00")],
                    write_conflict_ranges=[(b"k0", b"k0\x00")],
                    read_snapshot=stale_rv,
                    mutations=[Mutation(0, b"k0", b"stale")],
                ))

            # the recovery timeline is reconstructable from the
            # controller's status (the trace-file twin is pinned by the
            # chaos smoke lane)
            conn = transport.RpcConnection(ctrl.address)
            await conn.connect()
            st = json.loads((await conn.call(
                mp.TOKEN_STATUS, mp.StatusRequest(pad=0)
            )).payload)
            await conn.close()
            q = st["qos"]
            assert q["recovery_state"] == gen.FULLY_RECOVERED
            assert q["recoveries_completed"] >= 2  # recruitment + kill
            walk = [r["status"] for r in q["recovery_timeline"]
                    if r["epoch"] == q["epoch"]]
            assert walk[-len(gen.RECOVERY_STATES):] == list(
                gen.RECOVERY_STATES
            )
            await client.close()

        run(scenario())
    finally:
        for p in [ctrl, *workers]:
            p.stop()


# ---------------------------------------------------------------------------
# Sim/wire recovery parity (satellite): identical in-flight set, same
# commit/abort decisions.


def _inflight_set(stale_rv: int, fresh_rv: int) -> list[CommitTransaction]:
    """An in-flight mix around a recovery: stale readers (must abort),
    stale blind writes (no reads — commit), fresh readers (commit)."""
    mk = lambda rs, ws, snap: CommitTransaction(  # noqa: E731
        read_conflict_ranges=rs, write_conflict_ranges=ws,
        read_snapshot=snap,
    )
    kr = lambda k: [(k, k + b"\x00")]  # noqa: E731
    return [
        mk(kr(b"a"), kr(b"a"), stale_rv),      # stale RMW -> abort
        mk([], kr(b"b"), stale_rv),            # stale blind write -> commit
        mk(kr(b"c"), [], stale_rv),            # stale read-only -> abort
        mk(kr(b"d"), kr(b"d"), fresh_rv),      # fresh RMW -> commit
        mk([], kr(b"e"), fresh_rv),            # fresh blind write -> commit
        mk(kr(b"\xfe"), kr(b"\xfe"), stale_rv),  # stale, high key -> abort
    ]


def _sim_recovery_decisions(txns_for):
    """Run the ACTUAL sim recovery (cluster/recovery.py) and push the
    in-flight set through the new generation's proxy."""
    from foundationdb_tpu.cluster.commit_proxy import NotCommitted
    from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster

    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=1, n_resolvers=1, n_storage=1)
    )
    try:
        out = {}

        async def body():
            txn = db.create_transaction()
            txn.set(b"seed", b"s")
            await txn.commit()
            stale_rv = await db.create_transaction().get_read_version()
            p = cluster.commit_proxies[0]
            p.failed = RuntimeError("chaos")
            p.stop()
            await sched.delay(1.0)
            assert cluster.controller.epoch == 2
            fresh_rv = await db.create_transaction().get_read_version()
            decisions = []
            for t in txns_for(stale_rv, fresh_rv):
                try:
                    await cluster.commit_proxies[0].commit(t).future
                    decisions.append("commit")
                except NotCommitted:
                    decisions.append("abort")
            out["decisions"] = decisions
            out["rv"] = cluster.controller.gen.recovery_version

        sched.run_until(sched.spawn(body()).done)
        return out["decisions"], out["rv"]
    finally:
        cluster.stop()


@pytest.mark.parametrize("backend", ["native", "cpu"])
def test_sim_wire_recovery_parity(backend):
    """The wire conservative-abort first batch (generation.
    conservative_recovery_transaction through a real ResolverRole, the
    class the wire serves) decides an identical in-flight set exactly
    like the sim recovery — for the native skip list AND the kernel
    backend."""
    sim_decisions, _sim_rv = _sim_recovery_decisions(_inflight_set)

    # wire side: a freshly recruited resolver (EMPTY state, the
    # recovery contract) + the conservative first batch, then the same
    # in-flight set in one batch
    os.environ["RESOLVER_KERNEL"] = (
        "KernelConfig(max_key_bytes=16, max_txns=64, max_reads=256, "
        "max_writes=256, history_capacity=65536, "
        "window_versions=5000000)"
    )
    try:
        role = mp.ResolverRole(backend=backend, epoch=2)
    finally:
        os.environ.pop("RESOLVER_KERNEL", None)
    from foundationdb_tpu.models.types import ResolveTransactionBatchRequest

    recovery_version = 2_000_000
    stale_rv, fresh_rv = 1_000, recovery_version + 1_000

    async def wire():
        # boot (the controller's empty batch at the recovery version)
        await role.resolve(ResolveTransactionBatchRequest(
            prev_version=-1, version=recovery_version,
            last_received_version=-1, epoch=2,
        ))
        # the recovery transaction: conservative whole-keyspace write
        rep = await role.resolve(ResolveTransactionBatchRequest(
            prev_version=recovery_version,
            version=recovery_version + 1_000,
            last_received_version=recovery_version, epoch=2,
            transactions=[
                gen.conservative_recovery_transaction(recovery_version)
            ],
        ))
        assert rep.committed[0] == TransactionResult.COMMITTED
        # the identical in-flight set, one batch
        rep = await role.resolve(ResolveTransactionBatchRequest(
            prev_version=recovery_version + 1_000,
            version=recovery_version + 2_000,
            last_received_version=recovery_version + 1_000, epoch=2,
            transactions=_inflight_set(stale_rv, fresh_rv),
        ))
        return [
            "commit" if v == TransactionResult.COMMITTED else "abort"
            for v in rep.committed
        ]

    wire_decisions = run(wire())
    assert wire_decisions == sim_decisions, (
        f"sim {sim_decisions} != wire[{backend}] {wire_decisions}"
    )
    # and the expected shape, so a bug in BOTH paths can't hide
    assert sim_decisions == [
        "abort", "commit", "abort", "commit", "commit", "abort"
    ]


# ---------------------------------------------------------------------------
# Ratekeeper peer re-resolution (satellite): peers follow the
# controller's live topology; a re-recruited resolver's occupancy feed
# rejoins the admission law.


def test_ratekeeper_peers_follow_topology(tmp_path):
    """A RatekeeperRole with a controller re-resolves peers every
    control cycle: after the topology swaps the resolver address, the
    budget recovers from the saturated old resolver's clamp because the
    NEW resolver's (idle) occupancy feed replaces it — the pin for
    'budget recovers after a resolver is re-recruited'."""

    async def scenario():
        busy = {"occupancy": 1.5}

        async def topo_payload(state):
            return mp.TopologyReply(payload=json.dumps(state))

        # fake resolver servers: one saturated, one idle
        async def resolver_status(occ):
            return mp.StatusReply(payload=json.dumps({
                "role": "resolver",
                "qos": {"occupancy": occ, "queue_depth": 0},
            }))

        sock_a = str(tmp_path / "resA.sock")
        sock_b = str(tmp_path / "resB.sock")
        ctrl_sock = str(tmp_path / "ctrl.sock")
        srv_a = transport.RpcServer(sock_a)
        srv_a.register(
            mp.TOKEN_STATUS, lambda _r: resolver_status(busy["occupancy"])
        )
        srv_b = transport.RpcServer(sock_b)
        srv_b.register(mp.TOKEN_STATUS, lambda _r: resolver_status(0.0))
        topo_state = {
            "epoch": 1,
            "roles": {"resolver0": {"kind": "resolver", "address": sock_a}},
        }
        ctrl = transport.RpcServer(ctrl_sock)
        ctrl.register(mp.TOKEN_TOPOLOGY, lambda _r: topo_payload(topo_state))
        for s in (srv_a, srv_b, ctrl):
            await s.start()

        rk = mp.RatekeeperRole([], interval=0.05, controller=ctrl_sock)
        await rk.start()
        try:
            # cycle 1..n: peers resolve from topology -> the saturated
            # resolver clamps the budget
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                info = rk.law.rate_info()
                by = info.get("budget_limited_by") or {}
                if rk.peers == [sock_a] and "resolver" in str(
                    by.get("name", "")
                ):
                    break
                await asyncio.sleep(0.05)
            assert rk.peers == [sock_a]
            clamped = rk.law.rate_info()["transactions_per_second_limit"]

            # recovery: the topology swaps in a re-recruited resolver
            topo_state["epoch"] = 2
            topo_state["roles"] = {
                "resolver0": {"kind": "resolver", "address": sock_b}
            }
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if rk.peers == [sock_b] and rk.topology_epoch == 2:
                    budget = rk.law.rate_info()[
                        "transactions_per_second_limit"
                    ]
                    if budget > clamped * 1.5:
                        break
                await asyncio.sleep(0.05)
            assert rk.peers == [sock_b], "peer list did not re-resolve"
            assert rk.peer_refreshes >= 1
            budget = rk.law.rate_info()["transactions_per_second_limit"]
            assert budget > clamped * 1.5, (
                f"budget did not recover: {clamped} -> {budget}"
            )
        finally:
            await rk.stop()
            assert not rk._conns and not rk._controller_conns
            for s in (srv_a, srv_b, ctrl):
                await s.close()

    run(scenario())


# ---------------------------------------------------------------------------
# Push-on-death (ISSUE 14): the monitor's WorkerDeath notification must
# flag recovery immediately — no heartbeat-miss budget spent.


def test_worker_death_push_flags_recovery_immediately():
    ctrl = mp.ClusterControllerRole({"resolvers": 1})
    ctrl._needs_recovery = False  # steady state after initial recruitment
    ctrl.assignments = {
        "resolver0": {"kind": "resolver", "worker_id": "w1",
                      "address": "/tmp/x1.sock", "epoch": 3},
        "storage0": {"kind": "storage", "worker_id": "w2",
                     "address": "/tmp/x2.sock", "epoch": 3},
    }
    ctrl.workers = {
        "w1": {"worker_id": "w1", "address": "/tmp/x1.sock",
               "last_seen": time.monotonic()},
        "w2": {"worker_id": "w2", "address": "/tmp/x2.sock",
               "last_seen": time.monotonic()},
    }

    reply = run(ctrl.worker_death(mp.WorkerDeath(payload=json.dumps(
        {"worker_id": "w1", "kind": "worker", "rc": -9}
    ))))
    info = json.loads(reply.payload)
    assert info["roles"] == ["resolver0"]
    # a transaction-path death flags the recovery walk NOW, with the
    # push-attributed reason the chaos smoke pins
    assert ctrl._needs_recovery
    assert ctrl._recovery_reason == "push:resolver0"
    assert ctrl.death_notifications == 1
    # the dead worker can't be re-planned into the next generation
    assert "w1" not in ctrl.workers
    # the wake event cut the supervision sleep short
    assert ctrl._wake.is_set()


def test_worker_death_push_singleton_preloads_miss_budget():
    """A non-transaction-path death (storage/ratekeeper singletons)
    must NOT bump the generation; it pre-loads the heartbeat miss count
    so the next failed poll — not the third — re-recruits."""
    ctrl = mp.ClusterControllerRole({"resolvers": 1})
    ctrl._needs_recovery = False
    ctrl.assignments = {
        "storage0": {"kind": "storage", "worker_id": "w2",
                     "address": "/tmp/x2.sock", "epoch": 3},
    }
    ctrl.workers = {
        "w2": {"worker_id": "w2", "address": "/tmp/x2.sock",
               "last_seen": time.monotonic()},
    }
    run(ctrl.worker_death(mp.WorkerDeath(payload=json.dumps(
        {"worker_id": "w2", "kind": "worker", "rc": -9}
    ))))
    assert not ctrl._needs_recovery  # singletons re-recruit, no epoch bump
    assert ctrl._miss_counts["storage0"] >= ctrl.HEARTBEAT_MISSES
