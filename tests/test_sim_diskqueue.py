"""Sim disk stack: SimDiskQueue semantics + TLog crash/recovery/catch-up.

The sim analog of the native DiskQueue restart tests (test_restart.py):
acked (committed) records survive power loss; un-fsynced data may vanish
or tear but never corrupts recovery; a crashed log replica rebuilt from
its queue plus peer catch-up serves identical streams.
"""

from __future__ import annotations

import numpy as np
import pytest

from foundationdb_tpu.cluster.logsystem import LogSystem
from foundationdb_tpu.cluster.tlog import TLogCommitRequest
from foundationdb_tpu.runtime.flow import Scheduler
from foundationdb_tpu.sim.diskqueue import SimDiskQueue


def test_simdiskqueue_commit_recover_roundtrip():
    q = SimDiskQueue()
    s0 = q.push(b"alpha")
    s1 = q.push(b"beta")
    assert q.commit() == s1
    q.push(b"NEVER-COMMITTED")
    q.crash()  # un-fsynced data lost (no rng: nothing survives)
    assert q.recovered == [(s0, b"alpha"), (s1, b"beta")]
    s2 = q.push(b"gamma")
    assert s2 == s1 + 1
    q.commit()
    assert [d for _s, d in q.recovered] == [b"alpha", b"beta", b"gamma"]


def test_simdiskqueue_pop_discards_prefix():
    q = SimDiskQueue()
    seqs = [q.push(b"rec%d" % i) for i in range(10)]
    q.commit()
    q.pop(seqs[7])
    q.commit()
    assert [d for _s, d in q.recovered] == [b"rec7", b"rec8", b"rec9"]


def test_simdiskqueue_unsynced_pop_lost_on_crash():
    q = SimDiskQueue()
    seqs = [q.push(b"r%d" % i) for i in range(4)]
    q.commit()
    q.pop(seqs[2])  # NOT committed
    q.crash()
    # the pop was advisory and un-fsynced: recovery replays everything
    assert [d for _s, d in q.recovered] == [b"r0", b"r1", b"r2", b"r3"]


@pytest.mark.parametrize("seed", range(6))
def test_simdiskqueue_crash_prefix_semantics(seed):
    """After a crash, the survivors of the un-fsynced buffer are a
    PREFIX of it — never a gap, never reordered, never torn data."""
    rng = np.random.default_rng(seed)
    q = SimDiskQueue()
    q.push(b"durable")
    q.commit()
    for i in range(5):
        q.push(b"unsynced%d" % i)
    q.crash(rng)
    recs = [d for _s, d in q.recovered]
    assert recs[0] == b"durable"
    tail = recs[1:]
    assert tail == [b"unsynced%d" % i for i in range(len(tail))]


def _commit(sched, ls, prev, v, payload):
    req = TLogCommitRequest(
        prev_version=prev, version=v,
        messages={0: [payload], -1: [payload]},
        epoch=ls.epoch,
    )
    t = sched.spawn(ls.commit(req))
    sched.run_until(t.done)


def test_logsystem_crash_reboot_preserves_acked():
    sched = Scheduler(sim=True)
    ls = LogSystem(sched, n_logs=2)
    for i in range(6):
        _commit(sched, ls, i * 10, (i + 1) * 10, b"m%d" % i)

    rng = np.random.default_rng(3)
    ls.crash_and_reboot(1, rng)

    # the rebooted replica serves peeks identical to the survivor
    async def peek(i, after):
        return await ls.tlogs[i].peek(0, after)

    t0 = sched.spawn(peek(0, 0))
    sched.run_until(t0.done)
    t1 = sched.spawn(peek(1, 0))
    sched.run_until(t1.done)
    msgs0, _ = t0.done.get()
    msgs1, _ = t1.done.get()
    assert [v for v, _m in msgs0] == [v for v, _m in msgs1]
    assert len(msgs1) == 6

    # commits keep flowing through the rebooted replica
    _commit(sched, ls, 60, 70, b"after")
    assert ls.version.get() == 70


def test_logsystem_reboot_after_pops_replays_only_tail():
    sched = Scheduler(sim=True)
    ls = LogSystem(sched, n_logs=2)
    for i in range(8):
        _commit(sched, ls, i * 10, (i + 1) * 10, b"m%d" % i)
    ls.pop(0, 50)
    ls.pop(-1, 50, consumer="storage")  # stream tag unconstrained
    # pops ride un-fsynced; the next commit carries them to disk
    _commit(sched, ls, 80, 90, b"post")
    rng = np.random.default_rng(1)
    ls.crash_and_reboot(1, rng)
    rec = ls.tlogs[1].dq.recovered
    # restart cost proportional to the un-popped tail, not history
    assert 0 < len(rec) < 9
    _commit(sched, ls, 90, 100, b"post2")
    assert ls.version.get() == 100
