"""Tenant authorization tokens (fdbrpc/TokenSign + TokenCache +
design/authorization.md capability): signed expiring grants checked
before any tenant key resolves; forged/expired/wrong-tenant tokens are
permission_denied; verified tokens are cached by signature."""

import pytest

pytest.importorskip("cryptography")

from foundationdb_tpu.cluster import tenant as T
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.crypto.token_sign import (
    PermissionDeniedError,
    TokenVerifier,
    generate_keypair,
    sign_token,
)


@pytest.fixture
def world():
    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=1, n_storage=2)
    )
    key, pub = generate_keypair()
    cluster.token_verifier = TokenVerifier({"idp": pub})
    yield sched, cluster, db, key
    cluster.stop()


def drive(sched, coro):
    t = sched.spawn(coro, name="drive")
    sched.run_until(t.done)
    return t.done.get()


def test_valid_token_grants_access(world):
    sched, cluster, db, key = world

    async def body():
        await T.create_tenant(db, b"acme")
        tok = sign_token(
            key, tenants=[b"acme"], expires_at=sched.now() + 60,
            key_id="idp",
        )
        t = T.Tenant(db, b"acme", token=tok)
        async def w(txn):
            await txn.set(b"k", b"v")
        await t.run(w)
        txn = t.create_transaction()
        assert await txn.get(b"k") == b"v"
        # verification is CACHED by signature (TokenCache)
        assert cluster.token_verifier.verifies == 1
        return True

    assert drive(sched, body())


def test_missing_wrong_forged_expired_all_denied(world):
    sched, cluster, db, key = world

    async def body():
        await T.create_tenant(db, b"acme")
        await T.create_tenant(db, b"rival")
        # no token
        with pytest.raises(PermissionDeniedError):
            T.Tenant(db, b"acme").create_transaction()
        # token for a DIFFERENT tenant
        tok_rival = sign_token(
            key, tenants=[b"rival"], expires_at=sched.now() + 60,
            key_id="idp",
        )
        with pytest.raises(PermissionDeniedError):
            T.Tenant(db, b"acme", token=tok_rival).create_transaction()
        # forged: signed by an UNTRUSTED key under a trusted key id
        rogue_key, _ = generate_keypair()
        forged = sign_token(
            rogue_key, tenants=[b"acme"], expires_at=sched.now() + 60,
            key_id="idp",
        )
        with pytest.raises(PermissionDeniedError):
            T.Tenant(db, b"acme", token=forged).create_transaction()
        # expired
        # expiry runs on the SCHEDULER clock (determinism under sim)
        stale = sign_token(
            key, tenants=[b"acme"], expires_at=sched.now() - 0.001,
            key_id="idp",
        )
        with pytest.raises(PermissionDeniedError):
            T.Tenant(db, b"acme", token=stale).create_transaction()
        # tampered payload (tenant list edited post-signing)
        import base64

        good = sign_token(
            key, tenants=[b"rival"], expires_at=sched.now() + 60,
            key_id="idp",
        )
        payload, sig = good.split(b".", 1)
        edited = base64.b64encode(
            base64.b64decode(payload).replace(b"rival", b"acmee")[:-1]
        ) + b"." + sig
        with pytest.raises(PermissionDeniedError):
            T.Tenant(db, b"acme", token=edited).create_transaction()
        return True

    assert drive(sched, body())


def test_no_verifier_means_open_cluster(world):
    """Authorization is opt-in (the reference's default): without a
    verifier on the cluster, tenants work tokenless."""
    sched, cluster, db, _key = world
    cluster.token_verifier = None

    async def body():
        await T.create_tenant(db, b"open")
        t = T.Tenant(db, b"open")
        async def w(txn):
            await txn.set(b"k", b"v")
        await t.run(w)
        return True

    assert drive(sched, body())


def _sign_raw(private_key, payload: bytes) -> bytes:
    """Sign an ARBITRARY payload — the hostile/buggy identity-provider
    case: the signature is valid, the claims are garbage."""
    import base64

    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec

    sig = private_key.sign(payload, ec.ECDSA(hashes.SHA256()))
    return base64.b64encode(payload) + b"." + base64.b64encode(sig)


@pytest.mark.parametrize("payload", [
    b'[1, 2, 3]',                                            # non-dict JSON
    b'"just a string"',
    b'{}',                                                   # no claims at all
    b'{"kid": "default"}',                                   # missing exp/tenants
    b'{"kid": "default", "exp": "soon", "tenants": ["t"]}',  # string exp
    b'{"kid": "default", "exp": true, "tenants": ["t"]}',    # bool exp
    b'{"kid": 5, "exp": 1e18, "tenants": ["t"]}',            # non-string kid
    b'{"kid": "default", "exp": 1e18, "tenants": "t"}',      # tenants not a list
    b'{"kid": "default", "exp": 1e18, "tenants": [1, 2]}',   # non-string tenant
])
def test_validly_signed_malformed_claims_denied(payload):
    """A signature from a TRUSTED key over malformed claims must raise
    PermissionDeniedError — never a TypeError/KeyError escaping into
    the request path (ADVICE: token_sign malformed-claims hardening)."""
    key, pub = generate_keypair()
    verifier = TokenVerifier({"default": pub})
    token = _sign_raw(key, payload)
    with pytest.raises(PermissionDeniedError):
        verifier.check(token, b"t")
