"""Saturation telemetry: status qos schema pin, StatusRequest wire
codec, the shared assemble_status math, and fdbtop's polling/gating
paths against both deployment shapes (in-sim cluster and real OS role
processes over UDS)."""

import asyncio
import json
import os
import sys

import pytest

from foundationdb_tpu.cluster import multiprocess as mp
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.cluster.status import (
    assemble_status,
    cluster_status,
    performance_limited_by,
    qos_section,
)
from foundationdb_tpu.models.types import CommitTransaction
from foundationdb_tpu.wire import codec
from foundationdb_tpu.wire.codec import Mutation

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "scripts"),
)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# Schema pin: the qos keys every status consumer (fdbtop, the future
# Ratekeeper control loop) may rely on, for every role.

ROLE_QOS_KEYS = {
    "log": {"queue_mutations", "queue_bytes", "smoothed_queue_bytes",
            "input_bytes_per_s", "durability_lag_versions"},
    "storage": {"apply_lag_versions", "input_bytes_per_s",
                "fetch_backlog_ranges", "version_lag_versions",
                "mvcc_window_versions",
                # r20 hot-key telemetry: byte-sample totals, heatmap
                # rows, busiest-tag trackers
                "sampled_bytes", "sample_keys", "hot_ranges",
                "busiest_read_tag", "busiest_write_tag"},
    "resolver": {"queue_depth", "queue_depth_dist", "queue_wait_dist",
                 "compute_time_dist", "resolver_latency_dist",
                 "state_pressure", "occupancy",
                 # the r10 kernel panel (compile-cache counters, last
                 # compile seconds, stage p99s) — every backend
                 "kernel",
                 # r20: the conflict-range key sample sensor
                 "key_sample"},
    "commit_proxy": {"inflight_batches", "queued_requests",
                     "batches_started", "batch_sizer",
                     # r20: commit-side busiest write tag + the REAL
                     # per-tag fan-out state (PR-19 remaining (b))
                     "busiest_write_tag", "tag_partitioned"},
    "grv_proxy": {"queued_requests", "batch_sizer", "throttled_tags",
                  "sheds", "budget_stale", "max_queue"},
}

CLUSTER_QOS_KEYS = {
    "worst_queue_bytes_log_server", "worst_smoothed_queue_bytes_log_server",
    "worst_durability_lag_log_server", "worst_version_lag_storage_server",
    "worst_queue_depth_resolver", "worst_occupancy_resolver",
    "worst_queued_requests_commit_proxy",
    "worst_queued_requests_grv_proxy", "limiting_process",
    "performance_limited_by",
    # the Ratekeeper integration (r8: the live budget, its binding
    # limiter — one vocabulary with performance_limited_by — and the
    # fail-safe state)
    "transactions_per_second_limit", "max_tps", "min_tps",
    "worst_storage_lag_versions", "lag_target_versions",
    "lag_limit_versions", "tag_quotas", "auto_tag_quotas",
    "budget_limited_by", "budget_stale", "failsafe_tps",
}

#: cluster-LEVEL (next to qos, not inside it) r20 skew-rollup keys —
#: the skew-attribution gate's input, present on both status paths
CLUSTER_SAMPLING_KEYS = {"busiest_tags", "hot_ranges"}


@pytest.fixture(scope="module")
def sim_status():
    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=2, n_resolvers=2, n_storage=2,
                      n_tlogs=2)
    )

    async def body():
        for i in range(25):
            txn = db.create_transaction()
            txn.set(b"sat%03d" % i, b"v" * 64)
            await txn.commit()

    sched.run_until(sched.spawn(body()).done)
    status = cluster_status(cluster)
    cluster.stop()
    return status


def test_sim_status_qos_schema_pin(sim_status):
    """Every role instance carries its qos block with the pinned sensor
    keys; the cluster qos section carries worst-* + ratekeeper keys."""
    cl = sim_status["cluster"]
    assert CLUSTER_QOS_KEYS <= set(cl["qos"])
    assert CLUSTER_SAMPLING_KEYS <= set(cl)
    json.dumps(sim_status)  # the whole document stays JSON-able
    seen_roles = set()
    for name, block in cl["processes"].items():
        role = block["role"]
        if role in ROLE_QOS_KEYS:
            seen_roles.add(role)
            assert ROLE_QOS_KEYS[role] <= set(block["qos"]), (
                f"{name}: qos missing "
                f"{ROLE_QOS_KEYS[role] - set(block['qos'])}"
            )
    assert seen_roles == set(ROLE_QOS_KEYS)
    # run-loop utilization rides along (wall-clock, status-only)
    rl = cl["run_loop"]
    assert {"utilization", "busy_seconds", "steps",
            "slow_tasks", "slow_tasks_by_actor"} <= set(rl)
    assert 0.0 <= rl["utilization"] <= 1.0


def test_performance_limited_by_scoring():
    # healthy default below the 0.5 floor
    out = performance_limited_by([("tlog0", "log_server_write_queue", 0.2)])
    assert out["name"] == "workload" and out["reason_server_id"] == ""
    # the worst candidate past the floor names the process + reason
    out = performance_limited_by([
        ("tlog0", "log_server_write_queue", 0.7),
        ("storage1", "storage_server_durability_lag", 1.9),
        ("resolver0", "resolver_queue", 0.6),
    ])
    assert out["name"] == "storage_server_durability_lag"
    assert out["reason_server_id"] == "storage1"
    assert out["pressure"] == pytest.approx(1.9)


def test_qos_section_attribution_shifts_with_pressure():
    """The limiting-process attribution follows the saturated sensor —
    the acceptance shape (a saturation run shifts the attribution)."""
    from foundationdb_tpu.cluster.status import TLOG_QUEUE_BYTES_TARGET

    idle = qos_section(
        {"tlog0": {"queue_bytes": 0, "smoothed_queue_bytes": 0.0}},
        {"storage0": {"version_lag_versions": 0}},
        {"resolver0": {"queue_depth": 0}}, {}, {},
        lag_target=2e6,
    )
    assert idle["performance_limited_by"]["name"] == "workload"
    # saturate the tlog queue: attribution moves to the log server
    hot = qos_section(
        {"tlog0": {"queue_bytes": 2 * TLOG_QUEUE_BYTES_TARGET,
                   "smoothed_queue_bytes": 2.0 * TLOG_QUEUE_BYTES_TARGET}},
        {"storage0": {"version_lag_versions": 0}},
        {"resolver0": {"queue_depth": 0}}, {}, {},
        lag_target=2e6,
    )
    assert hot["performance_limited_by"]["name"] == "log_server_write_queue"
    assert hot["limiting_process"] == "tlog0"
    # now the resolver chain backs up PAST the tlog's pressure
    hot2 = qos_section(
        {"tlog0": {"smoothed_queue_bytes": 0.6 * TLOG_QUEUE_BYTES_TARGET}},
        {}, {"resolver0": {"queue_depth": 16}}, {}, {},
        lag_target=2e6,
    )
    assert hot2["performance_limited_by"]["name"] == "resolver_queue"
    assert hot2["limiting_process"] == "resolver0"
    # a compute-bound resolver: queue stays shallow (few, huge batches)
    # but its busy fraction pins — occupancy names it, not the queue
    hot3 = qos_section(
        {"tlog0": {"smoothed_queue_bytes": 0.6 * TLOG_QUEUE_BYTES_TARGET}},
        {}, {"resolver0": {"queue_depth": 1, "occupancy": 0.97}}, {}, {},
        lag_target=2e6,
    )
    assert hot3["performance_limited_by"]["name"] == "resolver_busy"
    assert hot3["limiting_process"] == "resolver0"
    assert hot3["worst_occupancy_resolver"] == pytest.approx(0.97)


def test_assemble_status_version_lag_join_and_degradation():
    procs = {
        "proxy0": {"role": "commit_proxy", "committed_version": 9000,
                   "qos": {"queued_requests": 1}},
        "storage0": {"role": "storage", "version": 2000, "qos": {}},
        "tlog0": {"role": "log", "version": 9000, "qos": {}},
        "mystery0": {"role": "wigglytuff", "qos": {}},  # unknown: ignored
        "bare0": {},  # no role, no qos: degrades, never crashes
    }
    doc = assemble_status(procs, lag_target=1000.0)
    q = doc["cluster"]["qos"]
    # the storage block was joined against the head (max committed/log)
    assert (doc["cluster"]["processes"]["storage0"]["qos"]
            ["version_lag_versions"] == 7000)
    assert q["worst_version_lag_storage_server"] == 7000
    # 7000/1000 lag pressure dominates -> storage names the limit
    assert q["performance_limited_by"]["name"] == (
        "storage_server_durability_lag"
    )
    assert q["limiting_process"] == "storage0"


def test_status_request_wire_codec_roundtrip():
    """StatusRequest/StatusReply survive encode->decode, including a
    nested JSON payload with non-ASCII and numeric edge values."""
    req = mp.StatusRequest(pad=0)
    blob = codec.encode(req)
    back = codec.decode(blob)
    assert isinstance(back, mp.StatusRequest) and back.pad == 0
    payload = json.dumps({
        "role": "log", "version": 2**53,
        "qos": {"smoothed_queue_bytes": 1234.5678,
                "names": ["ünïcode", "δ"], "flag": True, "none": None},
    })
    rep = mp.StatusReply(payload=payload)
    back = codec.decode(codec.encode(rep))
    assert isinstance(back, mp.StatusReply)
    assert json.loads(back.payload) == json.loads(payload)


def test_fdbtop_check_status_gate_both_directions():
    import fdbtop

    good = {
        "cluster": {
            "qos": {"performance_limited_by": {"name": "workload"}},
            # r20 skew rollup: the keys must exist at cluster level
            # (empty before traffic)
            "busiest_tags": [],
            "hot_ranges": [],
            "processes": {
                "tlog0": {"role": "log", "qos": {
                    "queue_bytes": 0, "smoothed_queue_bytes": 0.0,
                    "input_bytes_per_s": 0.0}},
                "storage0": {"role": "storage", "qos": {
                    "version_lag_versions": 0, "input_bytes_per_s": 0.0,
                    # r20 hot-key telemetry sensors
                    "sampled_bytes": 0, "sample_keys": 0,
                    "hot_ranges": [],
                    "busiest_read_tag": {"tag": None, "bytes_per_s": 0.0,
                                         "frac": 0.0},
                    "busiest_write_tag": {"tag": None, "bytes_per_s": 0.0,
                                          "frac": 0.0}}},
                "resolver0": {"role": "resolver", "qos": {
                    "queue_depth": 0, "queue_wait_dist": {},
                    "compute_time_dist": {}, "occupancy": 0.0,
                    "kernel": {"compile_cache_hits": 0,
                               "compile_cache_misses": 0,
                               "last_compile_seconds": 0.0,
                               "stage_p99_seconds": {},
                               # the r11 per-shard columns (dotted
                               # REQUIRED_SENSORS keys descend here)
                               "shards": 1,
                               "worst_shard_delta_occupancy": 0.0,
                               "worst_shard_main_occupancy": 0.0,
                               "collective_time_share": 0.0,
                               # r14 range-path counters
                               "spills": 0,
                               "sweep_groups": 0},
                    # r20: the conflict-range key sample
                    "key_sample": {"keys": 0, "top": []}}},
                "proxy0": {"role": "commit_proxy", "qos": {
                    "queued_requests": 0, "inflight_batches": 0,
                    "batch_sizer": {},
                    # r19 scale-out: grants consumed + partition mode
                    # (0/False on the legacy single-proxy path, but the
                    # KEYS are always present)
                    "version_grants": 0, "tag_partitioned": False,
                    # r20: commit-side busiest write tag
                    "busiest_write_tag": {"tag": None, "bytes_per_s": 0.0,
                                          "frac": 0.0}}},
                "sequencer0": {"role": "sequencer", "qos": {
                    "grants": 0, "grants_per_s": 0.0,
                    "live_committed_version": 0, "tags": 2,
                    "proxies_seen": 2}},
                "grv_proxy0": {"role": "grv_proxy",
                               "qos": {"queued_requests": 0, "sheds": 0,
                                       "budget_stale": False}},
                "ratekeeper0": {"role": "ratekeeper", "qos": {
                    "transactions_per_second_limit": 1e7,
                    "budget_limited_by": {"name": "workload"},
                    # r15: the law's binding-limiter streak (the
                    # elasticity trigger input) ships in rate_info
                    "binding_streak": {"name": "workload",
                                       "intervals": 1},
                    "budget_stale": False}},
            },
        }
    }
    require = ["log", "storage", "resolver", "commit_proxy", "grv_proxy",
               "ratekeeper", "sequencer"]
    assert fdbtop.check_status(good, require) == []
    # a missing role fails
    partial = json.loads(json.dumps(good))
    del partial["cluster"]["processes"]["resolver0"]
    assert any("resolver" in p for p in
               fdbtop.check_status(partial, require))
    # an empty qos block fails
    empty = json.loads(json.dumps(good))
    empty["cluster"]["processes"]["tlog0"]["qos"] = {}
    assert any("tlog0" in p for p in fdbtop.check_status(empty, require))
    # a missing sensor key fails
    missing = json.loads(json.dumps(good))
    del missing["cluster"]["processes"]["proxy0"]["qos"]["batch_sizer"]
    assert any("batch_sizer" in p for p in
               fdbtop.check_status(missing, require))
    # r19: a proxy that stopped reporting its grant counter fails, and
    # so does a sequencer missing its allotment surface
    nogrant = json.loads(json.dumps(good))
    del nogrant["cluster"]["processes"]["proxy0"]["qos"]["version_grants"]
    assert any("version_grants" in p for p in
               fdbtop.check_status(nogrant, require))
    noseq = json.loads(json.dumps(good))
    del noseq["cluster"]["processes"]["sequencer0"]["qos"]["proxies_seen"]
    assert any("proxies_seen" in p for p in
               fdbtop.check_status(noseq, require))
    # a missing DOTTED sensor (the r11 per-shard kernel columns) fails:
    # the gate descends into nested blocks
    noshard = json.loads(json.dumps(good))
    del noshard["cluster"]["processes"]["resolver0"]["qos"]["kernel"][
        "shards"
    ]
    assert any("kernel.shards" in p for p in
               fdbtop.check_status(noshard, require))
    # a missing performance_limited_by fails
    nolim = json.loads(json.dumps(good))
    nolim["cluster"]["qos"] = {}
    assert any("performance_limited_by" in p for p in
               fdbtop.check_status(nolim, require))
    # r20: a storage that stopped reporting its sampling sensors fails
    nosamp = json.loads(json.dumps(good))
    del nosamp["cluster"]["processes"]["storage0"]["qos"][
        "busiest_read_tag"
    ]
    assert any("busiest_read_tag" in p for p in
               fdbtop.check_status(nosamp, require))
    # r20: a resolver missing its key sample fails
    nokeys = json.loads(json.dumps(good))
    del nokeys["cluster"]["processes"]["resolver0"]["qos"]["key_sample"]
    assert any("key_sample" in p for p in
               fdbtop.check_status(nokeys, require))
    # r20: a document assembled without the skew rollup fails
    noroll = json.loads(json.dumps(good))
    del noroll["cluster"]["busiest_tags"]
    assert any("busiest_tags" in p for p in
               fdbtop.check_status(noroll, require))


def test_fdbtop_render_sim_status(sim_status):
    """The table renderer digests a full sim status document: one row
    per process, sparkline history column, limiting header."""
    import fdbtop

    histories = {}
    out1 = fdbtop.render(sim_status, histories, 0.0)
    out2 = fdbtop.render(sim_status, histories, 1.0)
    for name in sim_status["cluster"]["processes"]:
        assert name in out1
    assert "limited by" in out1
    assert "run loop" in out1
    # histories accumulate across frames
    assert all(len(h) == 2 for h in histories.values())
    assert "▁" in out2


# ---------------------------------------------------------------------------
# Wire mode: StatusRequest against real OS role processes, the parent's
# status socket, wire_cluster_status aggregation, and fdbtop's poll.


def test_wire_status_and_fdbtop_poll(tmp_path):
    """fdbtop --once --json shape against a live multiprocess cluster:
    every role (including both parent-side proxies) reports a qos
    entry, and the assembled document passes the smoke sensor gate."""
    import fdbtop

    procs = [
        mp.spawn_role("resolver", str(tmp_path)),
        mp.spawn_role("tlog", str(tmp_path)),
        mp.spawn_role("storage", str(tmp_path)),
    ]

    async def scenario():
        resolver = await mp.connect(procs[0].address)
        tlog = await mp.connect(procs[1].address)
        storage = await mp.connect(procs[2].address)
        pipe = mp.ProxyPipeline([resolver], tlog, storage,
                                batch_interval=0.001)
        pipe.start()
        server = mp.serve_status(str(tmp_path), pipe)
        await server.start()
        for i in range(20):
            k = b"w%02d" % i
            rv = await pipe.get_read_version()
            await pipe.commit(CommitTransaction(
                read_conflict_ranges=[(k, k + b"\x00")],
                write_conflict_ranges=[(k, k + b"\x00")],
                read_snapshot=rv,
                mutations=[Mutation(0, k, b"v" * 32)],
            ))
        # 1) direct RPC: every role process answers StatusRequest
        for conn, want_role in ((resolver, "resolver"), (tlog, "log"),
                                (storage, "storage")):
            rep = await conn.call(mp.TOKEN_STATUS, mp.StatusRequest(pad=0))
            block = json.loads(rep.payload)
            assert block["role"] == want_role and block["qos"]
        # 2) parent-side aggregation
        doc = await mp.wire_cluster_status(
            {"resolver0": resolver, "tlog0": tlog, "storage0": storage},
            pipe,
        )
        roles = {b["role"] for b in doc["cluster"]["processes"].values()}
        assert roles == {"resolver", "log", "storage",
                         "commit_proxy", "grv_proxy"}
        assert "performance_limited_by" in doc["cluster"]["qos"]
        # the tlog saw the workload's pushes (the RETAINED queue may
        # legitimately be empty here: the applier pops the log as
        # storage acks durability — PR 13's tail-sized restart rule)
        tblock = doc["cluster"]["processes"]["tlog0"]
        assert tblock["version"] > 0
        assert tblock["qos"]["queue_bytes"] >= 0
        # 3) fdbtop's own polling path over the socket dir (the
        #    --once --json engine), proxy0.sock GRV split included
        conns = {}
        try:
            top = await fdbtop._poll_wire(str(tmp_path), conns)
        finally:
            await fdbtop._close_conns(conns)
        assert fdbtop.check_status(
            top, ["log", "storage", "resolver", "commit_proxy",
                  "grv_proxy"]
        ) == []
        json.dumps(top)
        await pipe.stop()
        await server.close()
        for c in (resolver, tlog, storage):
            await c.close()

    try:
        run(scenario())
    finally:
        for p in procs:
            p.stop()


def test_saturated_resolver_shifts_wire_attribution(tmp_path):
    """Acceptance shape in miniature: park the resolver chain (a gap in
    prev_version never filled) so commit batches queue on resolution —
    the wire qos attribution must move off 'workload' onto the
    resolver."""
    procs = [mp.spawn_role("resolver", str(tmp_path))]

    async def scenario():
        from foundationdb_tpu.models.types import (
            ResolveTransactionBatchRequest,
        )

        resolver = await mp.connect(procs[0].address)
        # hole at prev_version=500: these requests park on the chain
        waiters = [
            asyncio.ensure_future(resolver.call(
                mp.TOKEN_RESOLVE,
                ResolveTransactionBatchRequest(
                    transactions=[], version=1000 + i,
                    prev_version=500 + i, last_received_version=0,
                ),
            ))
            for i in range(12)
        ]
        await asyncio.sleep(0.3)  # let them arrive and park
        rep = await resolver.call(mp.TOKEN_STATUS, mp.StatusRequest(pad=0))
        block = json.loads(rep.payload)
        assert block["qos"]["queue_depth"] >= 12
        doc = assemble_status({"resolver0": block})
        lim = doc["cluster"]["qos"]["performance_limited_by"]
        assert lim["name"] == "resolver_queue"
        assert lim["reason_server_id"] == "resolver0"
        for w in waiters:
            w.cancel()
        await asyncio.gather(*waiters, return_exceptions=True)
        await resolver.close()

    try:
        run(scenario())
    finally:
        for p in procs:
            p.stop()


def test_fdbtop_sim_once_json_smoke():
    """`fdbtop --sim --once --json --require ...` end to end in a
    subprocess: exit 0 and a parseable status document on stdout."""
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [_sys.executable, os.path.join(repo, "scripts", "fdbtop.py"),
         "--sim", "--once", "--json",
         "--require", "log,storage,resolver,commit_proxy,grv_proxy"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert "performance_limited_by" in doc["cluster"]["qos"]


def test_fdbtop_census_gate_and_columns():
    """r18: with census=True, every wire role process must report its
    resource-census block (fds/connections/servers/tasks) NEXT TO qos;
    grv_proxy is exempt (it rides proxy0's process). The render path
    turns the block into conns/tasks/fds columns."""
    import json

    import fdbtop

    census = {"fds": 11, "connections": 2, "servers": 1, "tasks": 5}
    none_tag = {"tag": None, "bytes_per_s": 0.0, "frac": 0.0}
    good = {
        "cluster": {
            "qos": {"performance_limited_by": {"name": "workload"}},
            "busiest_tags": [],
            "hot_ranges": [],
            "processes": {
                "storage0": {"role": "storage", "census": dict(census),
                             "qos": {"version_lag_versions": 0,
                                     "input_bytes_per_s": 0.0,
                                     "sampled_bytes": 0, "sample_keys": 0,
                                     "hot_ranges": [],
                                     "busiest_read_tag": dict(none_tag),
                                     "busiest_write_tag": dict(none_tag)}},
                "grv_proxy0": {"role": "grv_proxy",
                               "qos": {"queued_requests": 0, "sheds": 0,
                                       "budget_stale": False}},
            },
        }
    }
    require = ["storage", "grv_proxy"]
    assert fdbtop.check_status(good, require, census=True) == []
    # census off: the block is optional (sim rows don't carry one)
    bare = json.loads(json.dumps(good))
    del bare["cluster"]["processes"]["storage0"]["census"]
    assert fdbtop.check_status(bare, require) == []
    # census on: a missing gauge names the process and the dotted key
    partial = json.loads(json.dumps(good))
    del partial["cluster"]["processes"]["storage0"]["census"]["fds"]
    problems = fdbtop.check_status(partial, require, census=True)
    assert any("storage0" in p and "census.fds" in p for p in problems)
    # the render columns
    cols = dict(fdbtop._census_cols(good["cluster"]["processes"]
                                    ["storage0"]))
    assert cols == {"conns": 2, "tasks": 5, "fds": 11}
    assert fdbtop._census_cols({"role": "grv_proxy"}) == []


def test_sim_cluster_status_has_census(sim_status):
    """r18: the sim surfaces ONE cluster-level census (the whole sim is
    a single OS process) with the Scheduler's live-task gauge."""
    c = sim_status["cluster"]["census"]
    assert set(c) == {"fds", "connections", "servers", "tasks"}
    assert c["tasks"] >= 0 and c["fds"] >= -1
