"""LogSystem (replicated TLogs) tests."""

import pytest

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.cluster.logsystem import AllLogsDeadError


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


@pytest.fixture
def world():
    sched, cluster, db = open_cluster(ClusterConfig(n_tlogs=3, n_storage=2))
    yield sched, cluster, db
    cluster.stop()


def test_pushes_replicate_to_every_log(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        txn.set(b"lg", b"v")
        await txn.commit()
        await sched.delay(0.05)

    run(sched, body())
    versions = [t.version.get() for t in cluster.tlog.tlogs]
    assert len(set(versions)) == 1 and versions[0] > 0


def test_log_replica_failure_survivable(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        txn.set(b"pre", b"1")
        await txn.commit()

        cluster.kill_tlog(0)

        # commits and reads keep working on the survivors
        txn = db.create_transaction()
        txn.set(b"post", b"2")
        await txn.commit()
        txn = db.create_transaction()
        return await txn.get(b"pre"), await txn.get(b"post")

    assert run(sched, body()) == (b"1", b"2")
    # dead replica frozen strictly below the survivors
    dead_v = cluster.tlog.tlogs[0].version.get()
    live_v = cluster.tlog.tlogs[1].version.get()
    assert dead_v < live_v


def test_all_logs_dead_raises(world):
    sched, cluster, db = world
    cluster.kill_tlog(0)
    cluster.kill_tlog(1)
    with pytest.raises(AllLogsDeadError):
        cluster.kill_tlog(2)


def test_recovery_with_replicated_logs(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        txn.set(b"rk", b"1")
        await txn.commit()

        p = cluster.commit_proxies[0]
        p.failed = RuntimeError("kill")
        p.stop()
        await sched.delay(1.0)
        assert cluster.controller.epoch == 2

        async def w(txn):
            txn.set(b"rk2", b"2")

        await db.run(w)
        txn = db.create_transaction()
        return await txn.get(b"rk"), await txn.get(b"rk2")

    assert run(sched, body()) == (b"1", b"2")
    # every live log locked at the new epoch
    assert all(t.epoch == 2 for t in cluster.tlog.tlogs)
