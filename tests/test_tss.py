"""TSS mirror pairs + the LocationCache range map.

Reference capabilities: design/tss.md + fdbrpc/TSSComparison.h (a
testing storage server mirrors one SS, a read sample is duplicated and
compared out of the request path; mismatches are detected loudly and
never served), and NativeAPI's bounded location cache (range map with
eviction, not an unbounded scanned list)."""

import pytest

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.cluster.tss import TSS_SAMPLE_EVERY


@pytest.fixture
def world():
    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=1, n_storage=2, n_tss=1)
    )
    yield sched, cluster, db
    cluster.stop()


def drive(sched, coro):
    t = sched.spawn(coro, name="drive")
    sched.run_until(t.done)
    return t.done.get()


def test_tss_mirrors_and_matches(world):
    """A healthy TSS converges on identical content (same log tag) and
    sampled comparisons record zero mismatches."""
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        for i in range(8):
            txn.set(b"ts%02d" % i, b"v%d" % i)
        await txn.commit()
        await sched.delay(0.2)  # TSS pulls the same tag
        txn = db.create_transaction()
        rv = await txn.get_read_version()
        for i in range(4 * TSS_SAMPLE_EVERY):
            assert await txn.get(b"ts00") == b"v0"
        await sched.delay(0.2)  # comparisons drain
        return db.tss.samples, db.tss.mismatches

    samples, mismatches = drive(sched, body())
    assert samples >= 3  # the sampler genuinely fired
    assert mismatches == 0


def test_tss_detects_divergence(world):
    """Corrupt the TSS's store directly: sampled reads must flag the
    mismatch (SevError + counter) without affecting client results."""
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        txn.set(b"div", b"truth")
        await txn.commit()
        await sched.delay(0.2)
        # storage-engine divergence: the mirror silently corrupts
        tss = cluster.tss_servers[0]
        for hist in tss._hist.values():
            hist[:] = [(v, b"LIES") for v, _val in hist]
        txn = db.create_transaction()
        results = set()
        for i in range(4 * TSS_SAMPLE_EVERY):
            results.add(await txn.get(b"div"))
        await sched.delay(0.2)
        return results, db.tss.mismatches

    results, mismatches = drive(sched, body())
    assert results == {b"truth"}  # the app NEVER sees TSS data
    assert mismatches >= 1


def test_tss_death_never_blocks_reads(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        txn.set(b"alive", b"yes")
        await txn.commit()
        cluster.tss_servers[0].stop()
        txn = db.create_transaction()
        for i in range(4 * TSS_SAMPLE_EVERY):
            assert await txn.get(b"alive") == b"yes"
        return True

    assert drive(sched, body())


def test_location_cache_range_map_and_eviction():
    """The cache is a bisect range map with an eviction cap — covered
    lookups are hits, entries never grow unbounded (r4 verdict weak #8
    / NativeAPI locationCacheSize)."""
    from foundationdb_tpu.cluster.client import LocationCache

    sched, cluster, db = open_cluster(
        ClusterConfig(
            n_commit_proxies=1, n_storage=4,
            storage_boundaries=[b"g", b"n", b"t"],
        )
    )
    try:
        cache = LocationCache(cluster)
        cache.MAX_ENTRIES = 2
        b, e, team1 = cache.locate(b"aaa")
        assert cache.misses == 1
        # same shard: a HIT through the bisect map, not a re-fetch
        cache.locate(b"b")
        cache.locate(b"f")
        assert cache.hits == 2 and cache.misses == 1
        # distinct shards force eviction at the cap
        cache.locate(b"hh")
        cache.locate(b"pp")
        cache.locate(b"zz")
        assert cache.evictions >= 1
        assert len(cache._begins) <= 2
        # invalidation removes exactly the covering entry
        n_before = len(cache._begins)
        cache.locate(b"aaa")
        cache.invalidate(b"aaa")
        assert len(cache._begins) <= n_before
        _b, _e, _t = cache.locate(b"aaa")  # re-fetches after invalidate
        assert cache.misses >= 4
    finally:
        cluster.stop()
