"""Tuple layer, atomic ops, status JSON, resolution balancer tests."""

import json
import uuid

import pytest

from foundationdb_tpu.layers import tuple as fdbtuple
from foundationdb_tpu.layers.tuple import Subspace
from foundationdb_tpu.utils.atomic import apply_atomic


# -- tuple layer ----------------------------------------------------------

CASES = [
    (),
    (None,),
    (b"bytes", b"with\x00null"),
    ("unicode ☃",),
    (0, 1, -1, 255, 256, -255, -256, 2**48, -(2**48)),
    (3.14, -2.5, 0.0),
    (True, False),
    (uuid.UUID(int=0x1234567890ABCDEF1234567890ABCDEF),),
    (b"nested", ("inner", 42, None), b"after"),
]


@pytest.mark.parametrize("t", CASES)
def test_tuple_roundtrip(t):
    assert fdbtuple.unpack(fdbtuple.pack(t)) == t


def test_tuple_order_preserving():
    import random

    rng = random.Random(0)
    vals = []
    for _ in range(200):
        kind = rng.randrange(3)
        if kind == 0:
            vals.append((rng.randint(-2**40, 2**40),))
        elif kind == 1:
            vals.append((bytes(rng.randrange(256) for _ in range(rng.randrange(6))),))
        else:
            vals.append((rng.random() * 1000 - 500,))
    # within same type class, byte order == natural order
    ints = sorted(v for v in vals if isinstance(v[0], int))
    assert [fdbtuple.unpack(p) for p in sorted(fdbtuple.pack(v) for v in ints)] == ints
    floats = sorted(v for v in vals if isinstance(v[0], float))
    assert [
        fdbtuple.unpack(p) for p in sorted(fdbtuple.pack(v) for v in floats)
    ] == floats
    byteses = sorted(v for v in vals if isinstance(v[0], bytes))
    assert [
        fdbtuple.unpack(p) for p in sorted(fdbtuple.pack(v) for v in byteses)
    ] == byteses


def test_subspace():
    users = Subspace(("users",))
    k = users.pack((42, "alice"))
    assert users.contains(k)
    assert users.unpack(k) == (42, "alice")
    b, e = users.range()
    assert b < k < e
    sub = users[42]
    assert sub.pack(("alice",)) == k


# -- atomic op semantics --------------------------------------------------

def test_atomic_add_wraps_and_creates():
    assert apply_atomic("add", None, (5).to_bytes(8, "little")) == (5).to_bytes(8, "little")
    v = apply_atomic("add", (250).to_bytes(1, "little"), (10).to_bytes(1, "little"))
    assert v == (4).to_bytes(1, "little")  # wraps mod 256


def test_atomic_bitwise_and_minmax():
    assert apply_atomic("bit_and", None, b"\xff") == b"\x00"
    assert apply_atomic("bit_or", b"\x0f", b"\xf0") == b"\xff"
    assert apply_atomic("bit_xor", b"\xff", b"\x0f") == b"\xf0"
    assert apply_atomic("max", b"\x01\x00", b"\x02\x00") == b"\x02\x00"
    assert apply_atomic("min", b"\x01\x00", b"\x02\x00") == b"\x01\x00"
    assert apply_atomic("byte_max", b"a", b"b") == b"b"
    assert apply_atomic("byte_min", b"a", b"b") == b"a"
    assert apply_atomic("append_if_fits", b"ab", b"cd") == b"abcd"
    assert apply_atomic("compare_and_clear", b"x", b"x") is None
    assert apply_atomic("compare_and_clear", b"y", b"x") == b"y"


def test_atomic_through_cluster():
    from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster

    sched, cluster, db = open_cluster(ClusterConfig())

    async def body():
        txn = db.create_transaction()
        txn.add(b"ctr", 5)
        assert await txn.get(b"ctr") == (5).to_bytes(8, "little")  # RYW
        await txn.commit()

        txn = db.create_transaction()
        txn.add(b"ctr", -2)
        await txn.commit()

        txn = db.create_transaction()
        v = await txn.get(b"ctr")
        txn.atomic_op("byte_max", b"m", b"hello")
        txn.atomic_op("compare_and_clear", b"ctr", (3).to_bytes(8, "little"))
        await txn.commit()

        txn = db.create_transaction()
        return v, await txn.get(b"ctr"), await txn.get(b"m")

    v, ctr, m = sched.run_until(sched.spawn(body()).done)
    assert v == (3).to_bytes(8, "little")
    assert ctr is None  # compare_and_clear hit
    assert m == b"hello"
    cluster.stop()


# -- status + balancer ----------------------------------------------------

def test_status_json():
    from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
    from foundationdb_tpu.cluster.status import cluster_status

    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=2, n_resolvers=2)
    )

    async def body():
        txn = db.create_transaction()
        txn.set(b"s", b"1")
        await txn.commit()

    sched.run_until(sched.spawn(body()).done)
    st = cluster_status(cluster)
    json.dumps(st)  # must be JSON-able
    assert st["cluster"]["configuration"]["resolvers"] == 2
    assert st["cluster"]["workload"]["transactions"]["committed"] >= 1
    assert st["cluster"]["processes"]["resolver0"]["role"] == "resolver"
    assert st["cluster"]["live_committed_version"] > 0
    cluster.stop()


def test_balancer_moves_boundary_toward_hot_shard():
    from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster

    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=1, n_resolvers=2)
    )
    (orig_boundary,) = list(cluster.key_resolvers.boundaries)

    async def body():
        # hammer keys on resolver 0's shard (below the boundary)
        for i in range(30):
            txn = db.create_transaction()
            txn.set(b"\x01hot%02d" % (i % 10), b"x")
            await txn.get(b"\x01hot%02d" % ((i + 1) % 10))
            try:
                await txn.commit()
            except Exception:
                pass
        # let the balancer loop run
        await sched.delay(2.0)

    sched.run_until(sched.spawn(body()).done)
    assert cluster.balancer.counters.get("moves") >= 1
    assert cluster.key_resolvers.boundaries[0] != orig_boundary
    # cluster still works after the move
    async def after():
        txn = db.create_transaction()
        txn.set(b"\x01post", b"1")
        await txn.commit()
        txn = db.create_transaction()
        return await txn.get(b"\x01post")

    assert sched.run_until(sched.spawn(after()).done) == b"1"
    cluster.stop()
