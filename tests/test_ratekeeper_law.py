"""r8 admission-control law unit suite (the ISSUE-8 satellite): the
multi-input AdmissionController's contract — monotonicity, hysteresis,
anti-windup, fail-safe decay — plus the two r8 bugfix regressions
(all-dead liveness must clamp, auto tag quotas must not undercut a
management quota) and the decision-parity pin (throttling delays or
sheds at GRV only)."""

from __future__ import annotations

import pytest

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.cluster.ratekeeper import (
    AdmissionController,
    Ratekeeper,
)
from foundationdb_tpu.cluster.status import QOS_REASONS
from foundationdb_tpu.runtime.flow import Scheduler


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float = 0.25) -> None:
        self.t += dt


def make_law(**kw) -> tuple[AdmissionController, Clock]:
    clock = Clock()
    kw.setdefault("max_tps", 10_000.0)
    kw.setdefault("min_tps", 10.0)
    return AdmissionController(clock=clock, **kw), clock


def storage_slots(lag: float) -> dict:
    return {"storages": {"storage0": {"version_lag_versions": lag}}}


def resolver_slots(occ: float, queue: int = 0) -> dict:
    return {"resolvers": {"resolver0": {"occupancy": occ,
                                        "queue_depth": queue}}}


# ---------------------------------------------------------------------------
# Monotonicity: more lag / queue / occupancy never yields a BIGGER
# budget than less, stepping from identical state.


@pytest.mark.parametrize("slots_of, lo, hi", [
    (storage_slots, 1_000_000.0, 4_000_000.0),
    (lambda v: resolver_slots(v), 0.5, 1.8),
    (lambda v: resolver_slots(0.0, int(v)), 4, 40),
    (lambda v: {"tlogs": {"tlog0": {"smoothed_queue_bytes": v}}},
     16 << 20, 256 << 20),
    (lambda v: {"proxies": {"proxy0": {"queued_requests": v}}},
     1000, 20000),
])
def test_monotone_in_every_sensor(slots_of, lo, hi):
    budgets = []
    for v in (lo, hi):
        law, clock = make_law()
        clock.tick()
        budgets.append(
            law.update(slots_of(v), current_tps=500.0)
        )
    assert budgets[1] <= budgets[0], (
        f"worse sensor reading produced a BIGGER budget: {budgets}"
    )


def test_hard_lag_limit_clamps_to_min():
    law, clock = make_law()
    clock.tick()
    law.update(storage_slots(5_000_000.0), current_tps=5000.0)
    assert law.tps_budget == law.min_tps
    assert law.limited_by["name"] == "storage_server_durability_lag"
    assert law.limited_by["reason_server_id"] == "storage0"


def test_binding_limiter_names_share_status_vocabulary():
    """Every reason id the law can emit is a QOS_REASONS key — one
    vocabulary with status performance_limited_by."""
    cases = [
        (storage_slots(5_000_000.0), "storage_server_durability_lag"),
        (resolver_slots(1.5), "resolver_busy"),
        (resolver_slots(0.0, 40), "resolver_queue"),
        ({"tlogs": {"tlog0": {"smoothed_queue_bytes": 512 << 20}}},
         "log_server_write_queue"),
        ({"proxies": {"proxy0": {"queued_requests": 50_000}}},
         "commit_proxy_queue"),
    ]
    for slots, want in cases:
        law, clock = make_law()
        clock.tick()
        law.update(slots, current_tps=500.0)
        assert law.limited_by["name"] == want, (slots, law.limited_by)
        assert law.limited_by["name"] in QOS_REASONS
    law, clock = make_law()
    clock.tick()
    law.update({"storages": {}}, current_tps=500.0)
    assert law.limited_by["name"] == "workload"


# ---------------------------------------------------------------------------
# Hysteresis: a noisy sensor oscillating across the target boundary
# must not flap the budget between full speed and clamp.


def test_hysteresis_no_flap_across_target_boundary():
    law, clock = make_law()
    target = law.resolver_busy_target
    # engage hard once
    clock.tick()
    law.update(resolver_slots(1.5 * target), current_tps=500.0)
    engaged_budget = law.tps_budget
    assert engaged_budget < law.max_tps
    # noise oscillates +-5% around the target: above release_frac, so
    # the limiter stays ENGAGED — the budget may drift but must never
    # snap back to full speed, and step-to-step movement stays bounded
    prev = law.tps_budget
    for i in range(40):
        noisy = target * (1.05 if i % 2 == 0 else 0.95)
        clock.tick()
        law.update(resolver_slots(noisy), current_tps=500.0)
        assert law.tps_budget < law.max_tps, (
            f"budget flapped to full speed at step {i}"
        )
        assert law.tps_budget <= prev * law.growth_factor + law.min_tps
        prev = law.tps_budget
    # the sensor drops BELOW the release fraction: now it may release
    # and recover to full speed
    for _ in range(40):
        clock.tick()
        law.update(
            resolver_slots(0.5 * law.release_frac * target),
            current_tps=500.0,
        )
    assert law.tps_budget == law.max_tps


def test_hysteresis_is_per_process_not_per_reason():
    """A healthy peer must not release the engagement an overloaded
    process of the SAME role holds inside the hysteresis band."""
    law, clock = make_law()
    target = law.resolver_busy_target

    def two(hot_occ):
        return {"resolvers": {
            "resolver0": {"occupancy": hot_occ, "queue_depth": 0},
            # iterates AFTER resolver0: far below the release fraction
            "resolver1": {"occupancy": 0.01, "queue_depth": 0},
        }}

    clock.tick()
    law.update(two(1.4 * target), current_tps=500.0)
    assert law.tps_budget < law.max_tps
    # resolver0 drops into the band (above release, below target): the
    # idle resolver1 must not have released resolver0's engagement
    for _ in range(10):
        clock.tick()
        law.update(two(0.95 * target), current_tps=500.0)
        assert law.tps_budget < law.max_tps, (
            "idle peer released the hot process's engaged limiter"
        )


# ---------------------------------------------------------------------------
# Anti-windup: after load drops, the budget recovers to max within a
# bounded number of intervals — through intermediate values, never in
# one leap.


def test_anti_windup_bounded_recovery():
    law, clock = make_law()
    clock.tick()
    law.update(storage_slots(5_000_000.0), current_tps=5000.0)
    assert law.tps_budget == law.min_tps
    seen = [law.tps_budget]
    for _ in range(25):
        clock.tick()
        law.update(storage_slots(0.0), current_tps=100.0)
        # bounded growth per interval (no single leap to max)
        assert law.tps_budget <= (
            seen[-1] * law.growth_factor + law.min_tps
        )
        seen.append(law.tps_budget)
        if law.tps_budget == law.max_tps:
            break
    assert law.tps_budget == law.max_tps, (
        f"budget failed to recover within 25 intervals: {seen}"
    )
    assert len(seen) > 3  # through intermediate values


# ---------------------------------------------------------------------------
# Fail-safe: a stale sensor feed decays toward the conservative floor.


def test_failsafe_decay_on_stale_sensor_feed():
    law, clock = make_law()
    assert law.tps_budget == law.max_tps
    budgets = []
    for _ in range(20):
        clock.tick(0.5)
        budgets.append(law.update(None, current_tps=0.0))
    # monotone decay toward the floor — never frozen at full speed
    assert budgets[0] < law.max_tps
    assert all(b2 <= b1 for b1, b2 in zip(budgets, budgets[1:]))
    assert budgets[-1] == pytest.approx(law.failsafe_tps)
    assert law.failsafe_tps >= law.min_tps
    assert law.stale
    assert law.limited_by["name"] == "ratekeeper_failsafe"
    # fresh sensors: recovery resumes
    for _ in range(30):
        clock.tick()
        law.update({"storages": {}}, current_tps=100.0)
    assert law.tps_budget == law.max_tps and not law.stale


def test_failsafe_decay_never_raises_a_low_budget():
    law, clock = make_law()
    clock.tick()
    law.update(storage_slots(5_000_000.0), current_tps=100.0)
    assert law.tps_budget == law.min_tps
    clock.tick(5.0)
    law.update(None, current_tps=0.0)
    # the floor is a DECAY TARGET for a high budget, never a boost for
    # an already-clamped one
    assert law.tps_budget == law.min_tps


# ---------------------------------------------------------------------------
# r8 bugfix regression: all storage replicas dead must clamp, not
# admit at max (worst_lag over an empty live set reads 0.0).


def test_all_dead_liveness_clamps_to_min_tps():
    sched = Scheduler(sim=True)

    class SeqStub:
        class _N:
            def __init__(self):
                self.v = 10_000_000

            def get(self):
                return self.v

        def __init__(self):
            self.live_committed = self._N()

    class SSStub:
        def __init__(self):
            self.version = SeqStub._N()
            self.version.v = 0  # hugely lagged — but dead

    liveness = [False, False]
    rk = Ratekeeper(
        sched, SeqStub(), [SSStub(), SSStub()],
        interval=0.05, liveness=liveness,
    )
    rk.start()
    sched.run_for(0.5)
    # the old law: dead replicas excluded -> worst_lag 0.0 -> max_tps.
    # An all-dead cluster must fail SAFE instead.
    assert rk.worst_lag() == 0.0  # the trap input
    assert rk.get_rate_info() == rk.min_tps
    assert rk.law.limited_by["name"] == "ratekeeper_failsafe"
    # one replica reports back alive: the budget recovers
    liveness[0] = True
    rk.storage_servers[0].version.v = 10_000_000
    sched.run_for(2.0)
    assert rk.get_rate_info() == rk.max_tps
    rk.stop()


# ---------------------------------------------------------------------------
# r8 bugfix regression: the auto tag tier must never undercut an
# explicit management quota, and the lift path fires its probe.


def test_auto_tag_quota_never_undercuts_management_quota():
    sched = Scheduler(sim=True)

    class SeqStub:
        class _N:
            def __init__(self, v):
                self.v = v

            def get(self):
                return self.v

        def __init__(self):
            self.live_committed = self._N(5000)

    class SSStub:
        def __init__(self):
            self.version = SeqStub._N(0)

    rk = Ratekeeper(sched, SeqStub(), [SSStub()], interval=0.05,
                    lag_target=1000, lag_limit=10_000)
    rk.set_tag_quota("batch", 50.0)
    rk.start()

    async def drive():
        # heavy stress, "batch" dominating, across MANY intervals: the
        # old ratchet walked the auto quota monotonically down to
        # min_tag_tps (1.0), starving a tag the operator explicitly
        # granted 50 tps
        for _ in range(12):
            for _ in range(200):
                rk.note_tag_admission("batch")
            await sched.delay(0.05)
        return True

    t = sched.spawn(drive())
    sched.run_until(t.done)
    assert t.done.get()
    auto = rk.auto_tag_quotas.get("batch", float("inf"))
    assert auto >= 50.0, (
        f"auto quota {auto} undercut the explicit set_tag_quota(50)"
    )
    assert rk.get_tag_quota("batch") == 50.0
    rk.stop()


def test_auto_tag_quota_lift_fires_probe():
    from foundationdb_tpu.utils import probes

    sched = Scheduler(sim=True)

    class SeqStub:
        class _N:
            def __init__(self, v):
                self.v = v

            def get(self):
                return self.v

        def __init__(self):
            self.live_committed = self._N(5000)

    class SSStub:
        def __init__(self):
            self.version = SeqStub._N(0)

    seq, ss = SeqStub(), SSStub()
    rk = Ratekeeper(sched, seq, [ss], interval=0.05,
                    lag_target=1000, lag_limit=10_000)
    rk.start()
    before = probes.snapshot().get("ratekeeper.auto_tag_lifted", 0)

    async def drive():
        for _ in range(6):
            for _ in range(100):
                rk.note_tag_admission("hot")
            await sched.delay(0.05)
        assert rk.get_tag_quota("hot") < float("inf")
        ss.version.v = 5000  # stress clears
        for _ in range(30):
            await sched.delay(0.05)
            if rk.get_tag_quota("hot") == float("inf"):
                return True
        return False

    t = sched.spawn(drive())
    sched.run_until(t.done)
    assert t.done.get(), "auto quota never lifted after recovery"
    assert probes.snapshot().get("ratekeeper.auto_tag_lifted", 0) > before
    rk.stop()


# ---------------------------------------------------------------------------
# Consumer-side fail-safe + decision parity + shed retryability
# against a real sim cluster.


def test_dead_ratekeeper_decays_grv_budget_to_failsafe_floor():
    sched, cluster, db = open_cluster(ClusterConfig(n_storage=1))
    rk = cluster.ratekeeper
    grv = cluster.grv_proxy
    rk.stop()  # the Ratekeeper dies; its last budget was max_tps

    async def probe_grvs():
        # ~6s of virtual time: past the 1s staleness threshold plus the
        # ~3.5s the exponential decay (tau 0.5) needs to cross from
        # max_tps down to the failsafe floor
        for _ in range(60):
            txn = db.create_transaction()
            await txn.get_read_version()
            await sched.delay(0.1)

    t = sched.spawn(probe_grvs())
    sched.run_until(t.done)
    # well past the staleness threshold: the front door no longer
    # trusts the dead ratekeeper's full-speed budget
    assert grv._budget_stale
    assert grv._effective_tps <= rk.failsafe_tps
    assert grv._effective_tps >= rk.min_tps
    # restart: fresh budgets flow and the decay disengages
    rk.start()
    t2 = sched.spawn(probe_grvs())
    sched.run_until(t2.done)
    assert not grv._budget_stale
    cluster.stop()


def test_grv_shed_is_retryable_and_bounded():
    """The bounded queue sheds with the retryable error; db.run backs
    off and completes; shed requests never strand a promise."""
    from foundationdb_tpu.cluster.grv_proxy import GrvThrottledError

    sched, cluster, db = open_cluster(ClusterConfig(n_storage=1))
    grv = cluster.grv_proxy
    grv.max_queue = 4
    cluster.ratekeeper.tps_budget = 20.0
    cluster.ratekeeper.stop()  # budget frozen low; stale decay keeps it low

    sheds = [0]

    async def flood():
        # 40 bare GRVs against a 4-deep queue at ~20tps: most shed
        outcomes = []
        for _ in range(40):
            p = db.grv_proxy.get_read_version()
            outcomes.append(p)
        ok = err = 0
        for p in outcomes:
            try:
                await p.future
                ok += 1
            except GrvThrottledError:
                err += 1
        sheds[0] = err
        return ok

    t = sched.spawn(flood())
    sched.run_until(t.done)
    assert sheds[0] > 0, "bounded queue never shed"
    assert t.done.get() >= 1
    assert grv.counters.get("grvShed") == sheds[0]

    # and the client retry loop absorbs sheds transparently
    async def via_run():
        async def body(txn):
            txn.set(b"shed-ok", b"1")
        await db.run(body, max_retries=200)
        t2 = db.create_transaction()
        return await t2.get(b"shed-ok")

    t3 = sched.spawn(via_run())
    sched.run_until(t3.done)
    assert t3.done.get() == b"1"
    cluster.stop()


def test_decision_parity_throttle_gates_grv_only():
    """A transaction HOLDING a read version commits identically however
    hard the budget is clamped: admission control delays or sheds at
    GRV only — no resolver/commit-path coupling exists to change a
    committed/aborted decision for admitted transactions."""
    sched, cluster, db = open_cluster(ClusterConfig(n_storage=1))

    async def body():
        # pin read versions BEFORE the clamp
        t_ok = db.create_transaction()
        t_conflict = db.create_transaction()
        await t_ok.get_read_version()
        await t_conflict.get_read_version()
        # writer that will conflict with t_conflict's read
        w = db.create_transaction()
        w.set(b"parity", b"w")
        await w.commit()
        # clamp the budget to the floor and starve new admissions
        cluster.ratekeeper.stop()
        cluster.ratekeeper.tps_budget = cluster.ratekeeper.min_tps
        # admitted transactions still resolve EXACTLY as unthrottled:
        t_ok.set(b"parity-ok", b"1")
        v = await t_ok.commit()
        assert v > 0
        _ = await t_conflict.get(b"parity")  # conflicting read below w
        t_conflict.set(b"parity2", b"x")
        from foundationdb_tpu.cluster.commit_proxy import NotCommitted

        try:
            await t_conflict.commit()
            raise AssertionError(
                "stale read must conflict exactly as unthrottled"
            )
        except NotCommitted:
            pass
        return True

    t = sched.spawn(body())
    sched.run_until(t.done)
    assert t.done.get()
    cluster.stop()
