"""The interleaving auditor and schedule perturbation (runtime side of
flowcheck v2): lost-update detection on audited shared objects across
yield points, and seeded tie-break randomization among equally-runnable
actors — both pure additions that leave unaudited, unperturbed runs
byte-identical."""

import pytest

from foundationdb_tpu.runtime.flow import AuditedDict, Scheduler


def _spawn_rmw(sched, d, name, *, reread=False):
    async def actor():
        v = d["n"]
        await sched.delay(0.01)
        if reread:
            v = d["n"]
        # racy on purpose when reread=False: the auditor must flag it
        d["n"] = v + 1  # flowcheck: ignore[flow.rmw-across-wait]

    return sched.spawn(actor(), name=name)


# -- the auditor: both directions, asserted --------------------------------


def test_racy_rmw_across_await_is_flagged():
    """Two actors snapshot one audited key, yield, then write from the
    snapshot: the second writer lost the first's update — exactly one
    conflict, naming both actors."""
    sched = Scheduler(sim=True, audit=True)
    d = AuditedDict(sched, "shared", {"n": 0})
    _spawn_rmw(sched, d, "actor-a")
    _spawn_rmw(sched, d, "actor-b")
    sched.run_for(0.1)
    conflicts = sched.audit_conflicts()
    assert len(conflicts) == 1, conflicts
    c = conflicts[0]
    assert c["label"] == "shared" and c["key"] == "n"
    assert {c["actor"], c["writer"]} == {"actor-a", "actor-b"}
    assert c["read_step"] < c["write_step"] <= c["step"]
    # and the race really lost an update
    assert d._d["n"] == 1


def test_single_step_rmw_is_clean():
    """`d[k] = d[k] + 1` with no yield between read and write is atomic
    on a cooperative scheduler: never flagged."""
    sched = Scheduler(sim=True, audit=True)
    d = AuditedDict(sched, "shared", {"n": 0})

    async def atomic():
        await sched.delay(0.01)
        d["n"] = d["n"] + 1

    sched.spawn(atomic(), name="a")
    sched.spawn(atomic(), name="b")
    sched.run_for(0.1)
    assert sched.audit_conflicts() == []
    assert d._d["n"] == 2


def test_reread_after_wait_is_the_ordering_discipline():
    """Re-reading the slot after resuming (the handoff idiom — and the
    exact fix the static rule demands) clears the pending read: no
    conflict, no lost update."""
    sched = Scheduler(sim=True, audit=True)
    d = AuditedDict(sched, "shared", {"n": 0})
    _spawn_rmw(sched, d, "a", reread=True)
    _spawn_rmw(sched, d, "b", reread=True)
    sched.run_for(0.1)
    assert sched.audit_conflicts() == []
    assert d._d["n"] == 2


def test_auditor_off_records_nothing():
    sched = Scheduler(sim=True)  # audit defaults off
    d = AuditedDict(sched, "shared", {"n": 0})
    _spawn_rmw(sched, d, "a")
    _spawn_rmw(sched, d, "b")
    sched.run_for(0.1)
    assert sched.auditor is None
    assert sched.audit_conflicts() == []


def test_wildcard_iteration_conflicts_with_key_writes():
    """Aggregate reads (iteration) land on the '*' slot, which
    conflicts with per-key writes: iterate, yield, then write a key a
    peer wrote meanwhile -> flagged."""
    sched = Scheduler(sim=True, audit=True)
    d = AuditedDict(sched, "shared", {"x": 1})

    async def scanner():
        total = sum(1 for _ in d)  # wildcard read
        await sched.delay(0.02)
        d["x"] = total  # writes from the stale scan

    async def writer():
        await sched.delay(0.01)
        d["x"] = 99

    sched.spawn(scanner(), name="scanner")
    sched.spawn(writer(), name="writer")
    sched.run_for(0.1)
    conflicts = sched.audit_conflicts()
    assert [c["actor"] for c in conflicts] == ["scanner"]


def test_stale_clear_conflicts_with_foreign_key_writes():
    """The other wildcard direction: clear() from a stale scan wipes a
    peer's per-key write — a wildcard WRITE probes every recorded key
    of the label, so this lost update is flagged too."""
    sched = Scheduler(sim=True, audit=True)
    d = AuditedDict(sched, "shared", {"x": 1})

    async def sweeper():
        n = len(d)  # wildcard read
        await sched.delay(0.02)
        if n:
            d.clear()  # acts on the stale scan, wiping the peer's write

    async def writer():
        await sched.delay(0.01)
        d["x"] = 99

    sched.spawn(sweeper(), name="sweeper")
    sched.spawn(writer(), name="writer")
    sched.run_for(0.1)
    conflicts = sched.audit_conflicts()
    assert [c["actor"] for c in conflicts] == ["sweeper"], conflicts
    assert conflicts[0]["writer"] == "writer"


def test_stale_scan_flags_once_not_per_write():
    """A write consumes BOTH pending-read slots (exact key and the
    wildcard): one stale scan produces one conflict, not a duplicate
    against every later write the actor makes."""
    sched = Scheduler(sim=True, audit=True)
    d = AuditedDict(sched, "shared", {"x": 1, "y": 2})

    async def scanner():
        n = len(d)  # wildcard read
        await sched.delay(0.02)
        d["x"] = n      # first write: conflicts, consumes the scan
        await sched.delay(0.01)
        d["y"] = n      # later blind write: no pending read, no flag

    async def writer():
        await sched.delay(0.01)
        d["x"] = 9
        d["y"] = 9

    sched.spawn(scanner(), name="scanner")
    sched.spawn(writer(), name="writer")
    sched.run_for(0.1)
    assert len(sched.audit_conflicts()) == 1, sched.audit_conflicts()


def test_audited_dict_is_a_faithful_dict():
    sched = Scheduler(sim=True, audit=True)
    d = AuditedDict(sched, "x", {"a": 1})
    d["b"] = 2
    assert d["a"] == 1 and d.get("c") is None and "b" in d
    assert d.setdefault("c", 3) == 3 and d.pop("c") == 3
    d.update({"e": 5}, f=6)
    assert sorted(d.keys()) == ["a", "b", "e", "f"]
    assert len(d) == 4 and bool(d) and dict(d.items())["e"] == 5
    del d["f"]
    assert sorted(d) == ["a", "b", "e"]
    assert d == {"a": 1, "b": 2, "e": 5}
    d.clear()
    assert not d


# -- schedule perturbation -------------------------------------------------


def _tie_order(perturb_seed, n=6):
    sched = Scheduler(sim=True, perturb_seed=perturb_seed)
    log = []

    async def actor(i):
        await sched.delay(0.01)  # identical due + priority: a pure tie
        log.append(i)

    for i in range(n):
        sched.spawn(actor(i), name=f"t{i}")
    sched.run_for(0.1)
    return tuple(log)


def test_fifo_default_preserves_program_order():
    """perturb_seed=None is the historical order: ties resolve FIFO by
    sequence number, byte-identical to pre-perturbation schedulers."""
    assert _tie_order(None) == tuple(range(6))


def test_perturbation_reorders_ties_deterministically():
    orders = {k: _tie_order(k) for k in range(1, 6)}
    # each perturbed schedule is exactly reproducible...
    for k, o in orders.items():
        assert _tie_order(k) == o
    # ...permutes the same work...
    for o in orders.values():
        assert sorted(o) == list(range(6))
    # ...and at least one genuinely differs from FIFO (5 draws of a
    # 720-permutation space: astronomically certain)
    assert any(o != tuple(range(6)) for o in orders.values())


def test_perturbation_respects_time_and_priority():
    """Only EQUALLY-RUNNABLE entries reorder: different due times or
    priorities stay strictly ordered under any perturbation."""
    for k in (None, 1, 2, 3):
        sched = Scheduler(sim=True, perturb_seed=k)
        log = []

        async def late():
            await sched.delay(0.02)
            log.append("late")

        async def early():
            await sched.delay(0.01)
            log.append("early")

        sched.spawn(late(), name="late")
        sched.spawn(early(), name="early")
        sched.run_for(0.1)
        assert log == ["early", "late"], f"perturb={k}"


def test_perturbed_run_seed_is_reproducible_and_passes():
    """run_seed under a perturbation id: a legal schedule, so every
    gate holds, and the (seed, perturb) pair reproduces exactly."""
    from foundationdb_tpu.testing.soak import run_seed

    a = run_seed(7, perturb=1)
    assert a == run_seed(7, perturb=1)
    assert a[1] > 0  # committed work under the perturbed schedule


def test_race_selftest_fails_iff_auditor_armed():
    """The _corrupt_api-style divergence discipline for the auditor:
    the injected race fails the seed with the spec's auditor ON and
    passes with it OFF — both directions asserted."""
    import dataclasses

    from foundationdb_tpu.testing.soak import run_seed
    from foundationdb_tpu.testing.spec import load_spec

    with pytest.raises(AssertionError, match="interleaving conflict"):
        run_seed(3, _inject_race=True)  # default spec: audit = true
    off = load_spec("default")
    off = dataclasses.replace(
        off, policy={**off.policy, "audit": False}
    ).validate()
    assert run_seed(3, spec=off, _inject_race=True)


def test_pop_of_absent_key_is_not_a_phantom_write():
    """pop(absent, default) mutates nothing: it must not plant a
    last_write that frames this actor as the writer in a later
    conflict on a clean peer."""
    sched = Scheduler(sim=True, audit=True)
    d = AuditedDict(sched, "shared", {})

    async def popper():
        d.pop("k", None)  # absent: observation, not mutation

    async def rmw():
        v = d.get("k")
        await sched.delay(0.02)
        d["k"] = (v or 0) + 1  # flowcheck: ignore[flow.rmw-across-wait] (single writer; the test is about pop)

    sched.spawn(rmw(), name="rmw")
    sched.spawn(popper(), name="popper")
    sched.run_for(0.1)
    assert sched.audit_conflicts() == []
    # a REAL pop is still a write: the same shape with the key present
    sched2 = Scheduler(sim=True, audit=True)
    d2 = AuditedDict(sched2, "shared", {"k": 1})

    async def rmw2():
        v = d2.get("k")
        await sched2.delay(0.02)
        d2["k"] = (v or 0) + 1  # flowcheck: ignore[flow.rmw-across-wait] (the race IS the fixture)

    async def popper2():
        await sched2.delay(0.01)
        d2.pop("k", None)

    sched2.spawn(rmw2(), name="rmw")
    sched2.spawn(popper2(), name="popper")
    sched2.run_for(0.1)
    assert [c["writer"] for c in sched2.audit_conflicts()] == ["popper"]
