"""resolver_backend knob tests: the CPU path beside the TPU path."""

import numpy as np
import pytest

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.config import TEST_CONFIG
from foundationdb_tpu.models.conflict_set import (
    CpuConflictSet,
    TpuConflictSet,
    make_conflict_set,
)
from foundationdb_tpu.testing import workloads


def test_knob_gate_selects_backend():
    from foundationdb_tpu.utils.knobs import SERVER_KNOBS

    # "tpu" auto-routes SMALL configs to the CPU backend (the measured
    # latency-regime threshold, RESOLVER_TPU_MIN_BATCH): TEST_CONFIG's
    # capacity sits far below it
    assert TEST_CONFIG.max_txns < SERVER_KNOBS.RESOLVER_TPU_MIN_BATCH
    assert isinstance(make_conflict_set(TEST_CONFIG, "tpu"), CpuConflictSet)
    assert isinstance(make_conflict_set(TEST_CONFIG), CpuConflictSet)
    # lowering the threshold sends the same config to the device path
    old = SERVER_KNOBS.RESOLVER_TPU_MIN_BATCH
    try:
        SERVER_KNOBS.set("RESOLVER_TPU_MIN_BATCH", 1)
        assert isinstance(make_conflict_set(TEST_CONFIG, "tpu"), TpuConflictSet)
    finally:
        SERVER_KNOBS.set("RESOLVER_TPU_MIN_BATCH", old)
    # "tpu-force" bypasses the threshold outright
    assert isinstance(
        make_conflict_set(TEST_CONFIG, "tpu-force"), TpuConflictSet
    )
    assert isinstance(make_conflict_set(TEST_CONFIG, "cpu"), CpuConflictSet)
    with pytest.raises(ValueError):
        make_conflict_set(TEST_CONFIG, "gpu")


def test_backends_agree_on_random_workload():
    rng = np.random.default_rng(5)
    wcfg = workloads.WorkloadConfig(n_txns=24, keyspace=32, report_fraction=1.0)
    tpu = make_conflict_set(TEST_CONFIG, "tpu-force")
    cpu = make_conflict_set(TEST_CONFIG, "cpu")
    version = 0
    for _ in range(6):
        version += 13
        txns = workloads.make_batch(rng, wcfg, version, TEST_CONFIG.window_versions)
        a = tpu.resolve(txns, version)
        b = cpu.resolve(txns, version)
        assert [int(v) for v in a.verdicts] == [int(v) for v in b.verdicts]
        assert a.conflicting_key_ranges == b.conflicting_key_ranges


def test_cluster_runs_on_cpu_backend():
    sched, cluster, db = open_cluster(
        ClusterConfig(n_resolvers=2, resolver_backend="cpu")
    )

    async def body():
        txn = db.create_transaction()
        txn.set(b"cpu", b"backend")
        await txn.commit()

        t1 = db.create_transaction()
        t2 = db.create_transaction()
        v1 = await t1.get(b"cpu")
        await t2.get(b"cpu")
        t1.set(b"cpu", b"one")
        t2.set(b"cpu", b"two")
        await t1.commit()
        from foundationdb_tpu.cluster.commit_proxy import NotCommitted

        try:
            await t2.commit()
            return v1, "both"
        except NotCommitted:
            return v1, "conflict"

    v1, outcome = sched.run_until(sched.spawn(body()).done)
    assert v1 == b"backend"
    assert outcome == "conflict"
    from foundationdb_tpu.models.conflict_set import CpuConflictSet as C

    assert all(isinstance(r.conflict_set, C) for r in cluster.resolvers)
    cluster.stop()
