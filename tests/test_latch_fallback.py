"""fixpoint_latch contract: trips never externalize wrong verdicts.

The latched group kernel (ops/group.py, fixpoint_latch=True) REFUSES a
group whose intra-batch conflict chains run deeper than fixpoint_unroll:
GroupVerdict.unconverged trips and the returned state is the unchanged
input state. The host contract (ADVICE r4 + VERDICT r4 task 5):

* TpuConflictSet.resolve_group_args (default check_latch=True) must
  detect the trip and auto-redispatch the SAME args on the exact
  while-loop kernel — callers see correct verdicts, never the latched
  garbage.
* prewarm_exact compiles the exact program up front so the fallback is
  a program swap, not an XLA compile stall mid-version-chain (the
  reference resolver never stalls its chain, Resolver.actor.cpp:283-296).

Runs on the CPU lane (conftest pins JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from foundationdb_tpu.config import KernelConfig
from foundationdb_tpu.models.conflict_set import (
    TpuConflictSet,
    _resolve_group_jit,
)
from foundationdb_tpu.models.types import CommitTransaction
from foundationdb_tpu.utils import packing
from foundationdb_tpu.utils.packing import stack_device_args

pytestmark = pytest.mark.kernel


def chain_batch(config, n, version, snapshot):
    """One batch whose txns form a conflict chain of depth n:
    t0 writes k0; t_i reads k_{i-1} and writes k_i. Sequentially every
    txn commits (each reads the PRE-batch value), but the alternating
    fixpoint needs ~n applications to prove it — deeper than a small
    unroll, so the latch trips."""
    txns = []
    key = lambda i: b"k%04d" % i
    for i in range(n):
        txns.append(
            CommitTransaction(
                read_conflict_ranges=(
                    [] if i == 0 else [(key(i - 1), key(i - 1) + b"\x00")]
                ),
                write_conflict_ranges=[(key(i), key(i) + b"\x00")],
                read_snapshot=snapshot,
            )
        )
    return packing.pack_batch(txns, version, 0, config)


def cfg(**kw):
    d = dict(
        max_key_bytes=8, max_txns=16, max_reads=16, max_writes=16,
        history_capacity=256, window_versions=10_000,
        fixpoint_unroll=1, fixpoint_latch=True,
    )
    d.update(kw)
    return KernelConfig(**d)


def test_latch_trips_and_autoredispatch_matches_exact():
    config = cfg()
    exact = dataclasses.replace(config, fixpoint_latch=False)
    batches = [
        chain_batch(config, 10, version=100, snapshot=50),
        chain_batch(config, 10, version=200, snapshot=150),
    ]
    stacked = stack_device_args(batches)

    # raw latched kernel refuses: unconverged trips, state unchanged
    cs_raw = TpuConflictSet(config)
    before = np.asarray(cs_raw.state.main_keys).copy()
    outs_raw = cs_raw.resolve_group_args(stacked, check_latch=False)
    assert bool(np.asarray(outs_raw.unconverged).any())
    np.testing.assert_array_equal(
        np.asarray(cs_raw.state.main_keys), before
    )

    # default path: auto-redispatch serves the exact kernel's decisions
    cs = TpuConflictSet(config)
    outs = cs.resolve_group_args(stacked)
    assert not bool(np.asarray(outs.unconverged).any())

    cs_exact = TpuConflictSet(exact)
    ref = cs_exact.resolve_group_args(stacked)
    np.testing.assert_array_equal(
        np.asarray(outs.verdict), np.asarray(ref.verdict)
    )
    # ... and the post-group history state matches the exact kernel's
    np.testing.assert_array_equal(
        np.asarray(cs.state.main_keys), np.asarray(cs_exact.state.main_keys)
    )
    np.testing.assert_array_equal(
        np.asarray(cs.state.main_ver), np.asarray(cs_exact.state.main_ver)
    )


def test_prewarm_exact_avoids_fallback_compile():
    config = cfg()
    batches = [chain_batch(config, 10, version=100, snapshot=50)]
    stacked = stack_device_args(batches)

    cs = TpuConflictSet(config)
    cs.prewarm_exact(stacked)
    fn = _resolve_group_jit(0, config.fixpoint_unroll, False)
    warmed = fn._cache_size()
    assert warmed >= 1

    # the trip + fallback must hit the warmed program, not compile anew
    outs = cs.resolve_group_args(stacked)
    assert not bool(np.asarray(outs.unconverged).any())
    assert fn._cache_size() == warmed


def test_shallow_group_never_trips():
    # unroll=3 covers a depth-2 chain: no trip, no redispatch needed
    config = cfg(fixpoint_unroll=3)
    batches = [chain_batch(config, 3, version=100, snapshot=50)]
    stacked = stack_device_args(batches)
    cs = TpuConflictSet(config)
    outs = cs.resolve_group_args(stacked, check_latch=False)
    assert not bool(np.asarray(outs.unconverged).any())
