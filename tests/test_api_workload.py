"""The full-client ApiCorrectness workload + sequential-model checker
(testing/api_workload.py): clean-cluster runs on both resolver
backends, the client's reverse/limited range-read contract, and the
self-tests proving every checker direction actually fails a seed."""

import numpy as np
import pytest

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.runtime.flow import all_of
from foundationdb_tpu.testing.api_workload import (
    DATA,
    ApiWorkload,
    TxnRecord,
)
from foundationdb_tpu.testing.oracle import SequentialModel


def _stamp(version: int, order: int = 0) -> bytes:
    return version.to_bytes(8, "big") + order.to_bytes(2, "big")


def run_api(seed=5, backend="cpu", *, actors=3, rounds=10, corrupt=False,
            sabotage_first_commit=False):
    sched, cluster, db = open_cluster(
        ClusterConfig(
            n_commit_proxies=2, n_resolvers=2, n_storage=2,
            sim_seed=seed, resolver_backend=backend,
        )
    )
    try:
        if sabotage_first_commit:
            proxy = cluster.commit_proxies[0]
            real_commit = proxy.commit
            fired = []

            def sabotaged_commit(ctr):
                from foundationdb_tpu.cluster.commit_proxy import (
                    CommitUnknownResult,
                )
                from foundationdb_tpu.runtime.flow import Promise

                p = real_commit(ctr)
                if not fired:
                    fired.append(True)
                    broken = Promise()

                    def relay(f):
                        if not broken.is_set:
                            broken.send_error(CommitUnknownResult())

                    p.future.add_done_callback(relay)
                    return broken
                return p

            proxy.commit = sabotaged_commit
        # no fault injection on this cluster -> the strict abort audit
        # is sound (phantom resolver state needs kill faults); sabotage
        # produces an unknown outcome, which disarms it internally
        api = ApiWorkload(
            sched, db, seed, actors=actors, rounds=rounds,
            strict_aborts=True,
        )
        tasks = [
            sched.spawn(c, name=f"api-{i}").done
            for i, c in enumerate(api.actor_coros())
        ]
        sched.run_until(all_of(tasks))
        sched.run_for(1.0)
        if corrupt:
            api.corrupt_for_selftest(cluster)
        sched.run_until(sched.spawn(api.verify()).done)
        return api
    finally:
        cluster.stop()


def test_api_workload_clean_cluster_cpu():
    api = run_api(seed=5)
    s = api.stats
    assert s["acked"] > 0 and s["reads_checked"] > 0
    # rerun-identical (the unseed determinism contract)
    assert run_api(seed=5).signature() == api.signature()


def test_api_workload_exercises_the_surface():
    """Across a few clean seeds the workload must genuinely reach the
    API surface it claims to check: conflicts, snapshot reads, reverse
    scans, atomics, versionstamps, explicit conflict ranges."""
    kinds = set()
    conflicts = 0
    for seed in (5, 6, 7, 8):
        api = run_api(seed=seed)
        conflicts += api.stats["conflict"]
        for rec in api.records:
            for op, _obs in rec.ops:
                k = op[0]
                if k == "range" and op[4]:
                    k = "range.reverse"
                elif k == "range" and op[3] < (1 << 30):
                    k = "range.limited"
                elif k == "get" and op[2]:
                    k = "get.snapshot"
                kinds.add(k)
    assert conflicts > 0, "no transaction ever conflicted"
    for needed in ("get", "get.snapshot", "range", "range.reverse",
                   "range.limited", "set", "clear_range", "atomic",
                   "rcr", "wcr", "vs_value", "vs_key", "sysread"):
        assert needed in kinds, f"workload never generated {needed}"


@pytest.mark.kernel
def test_api_workload_clean_cluster_tpu_kernel():
    """The same workload through the JAX conflict kernel (tpu-force
    routes unconditionally; JAX_PLATFORMS=cpu compiles it on host)."""
    api = run_api(seed=5, backend="tpu-force", rounds=8)
    assert api.stats["acked"] > 0 and api.stats["reads_checked"] > 0


def test_injected_divergence_fails_the_run():
    """The divergence self-test: values corrupted on every replica
    BEHIND the transaction system must fail the model cross-check."""
    with pytest.raises(AssertionError, match="api model divergence"):
        run_api(seed=5, corrupt=True)


def test_injected_divergence_fails_the_ensemble_seed():
    """Same self-test through the soak ensemble: run_seed's _corrupt_api
    hook must fail the seed (the smoke spec runs the api workload on
    every seed), and the identical seed passes without it."""
    from foundationdb_tpu.testing import soak

    assert soak.run_seed(1, spec="smoke")
    with pytest.raises(AssertionError, match="api model divergence"):
        soak.run_seed(1, spec="smoke", _corrupt_api=True)


def test_unknown_result_resolved_by_marker():
    """A commit the client saw as commit_unknown_result but that really
    landed is resolved to COMMITTED by its versionstamped marker and
    enters the model (no possible-value ambiguity)."""
    api = run_api(seed=11, sabotage_first_commit=True)
    assert api.stats["unknown"] >= 1
    assert api.stats["unknown_resolved"] >= 1


def test_false_commit_audit_fires():
    """Checker self-test: a fabricated committed pair where the later
    transaction read a range an earlier commit (above its read
    version) wrote must be flagged as a serializability violation."""
    api = ApiWorkload(None, None, 0)
    writer = TxnRecord(actor=0, n=0)
    writer.outcome = "acked"
    writer.read_version = 1
    writer.write_conflicts = [(DATA + b"05", DATA + b"05\x00")]
    reader = TxnRecord(actor=1, n=0)
    reader.outcome = "acked"
    reader.read_version = 5  # BELOW the writer's commit version
    reader.read_conflicts = [(DATA + b"00", DATA + b"09")]
    reader.write_conflicts = [(DATA + b"20", DATA + b"20\x00")]
    committed = [(_stamp(8), writer), (_stamp(12), reader)]
    with pytest.raises(AssertionError, match="FALSE COMMIT"):
        api._check_decisions(committed)
    # with the writer BELOW the reader's snapshot there is no violation
    reader.read_version = 9
    api._check_decisions(committed)


def test_false_abort_audit_fires():
    """Checker self-test: under strict mode a NotCommitted with no
    conflicting committed writer anywhere is a false abort."""
    api = ApiWorkload(None, None, 0, strict_aborts=True)
    aborted = TxnRecord(actor=0, n=0)
    aborted.outcome = "conflict"
    aborted.read_version = 5
    aborted.read_conflicts = [(DATA + b"00", DATA + b"01")]
    api.records = [aborted]
    with pytest.raises(AssertionError, match="FALSE ABORT"):
        api._check_decisions([])
    # a conflicting committed writer explains the abort
    writer = TxnRecord(actor=1, n=0)
    writer.outcome = "acked"
    writer.read_version = 1
    writer.write_conflicts = [(DATA + b"00", DATA + b"00\x00")]
    api._check_decisions([(_stamp(9), writer)])


def test_read_divergence_detected_against_model():
    """Checker self-test: a recorded read that disagrees with the
    sequential model at its read version is flagged."""
    api = ApiWorkload(None, None, 0)
    model = SequentialModel()
    model.apply(_stamp(5), [("set", DATA + b"00", b"truth")])
    rec = TxnRecord(actor=0, n=0)
    rec.outcome = "conflict"  # even failed txns' reads are checked
    rec.read_version = 7
    rec.ops = [(("get", DATA + b"00", False), b"LIES")]
    rec.read_conflicts = [(DATA + b"00", DATA + b"00\x00")]
    with pytest.raises(AssertionError, match="model says"):
        api._check_txn(rec, model)
    rec.ops = [(("get", DATA + b"00", False), b"truth")]
    api._check_txn(rec, model)
    # ...and at a snapshot BELOW the commit the key must be absent
    rec.read_version = 4
    rec.ops = [(("get", DATA + b"00", False), None)]
    api._check_txn(rec, model)


def test_conflict_range_contract_detected():
    """Checker self-test: a transaction whose sent conflict ranges
    disagree with what its ops imply (e.g. a wrongly narrowed range)
    is flagged even when every read value matches."""
    api = ApiWorkload(None, None, 0)
    model = SequentialModel()
    rec = TxnRecord(actor=0, n=0)
    rec.outcome = "acked"
    rec.read_version = 7
    rec.ops = [(("get", DATA + b"00", False), None)]
    rec.read_conflicts = []  # client "forgot" the implicit point range
    with pytest.raises(AssertionError, match="read-conflict contract"):
        api._check_txn(rec, model)


def test_sequential_model_versionstamps_and_ordering():
    m = SequentialModel()
    # inserted out of order; replay is stamp-ordered
    m.apply(_stamp(20, 1), [("set", b"api/k/a", b"late")])
    m.apply(_stamp(10), [
        ("set", b"api/k/a", b"early"),
        ("vs_key", b"api/vs/p", b"/sfx", b"vk"),
        ("vs_value", b"api/k/b", b"pre-"),
    ])
    m.apply(_stamp(20, 0), [("atomic", "add", b"api/k/c", b"\x05")])
    s = m.final_state()
    assert s[b"api/k/a"] == b"late"
    assert s[b"api/vs/p" + _stamp(10) + b"/sfx"] == b"vk"
    assert s[b"api/k/b"] == b"pre-" + _stamp(10)
    assert s[b"api/k/c"] == b"\x05"
    # visibility boundary: a commit at version v is visible AT v
    assert m.state_at(9) == {}
    assert m.state_at(10)[b"api/k/a"] == b"early"
    # same-version batch order applies in order
    m.apply(_stamp(30, 0), [("set", b"api/k/a", b"first")])
    m.apply(_stamp(30, 2), [("set", b"api/k/a", b"second")])
    assert m.state_at(30)[b"api/k/a"] == b"second"
    with pytest.raises(ValueError):
        m.apply(_stamp(10), [])  # duplicate stamp


def test_reverse_and_limited_range_reads():
    """The client reverse/limit contract directly: result order, limit
    selection from the END, RYW overlay, and conflict-range narrowing
    ([lowest returned, end) for a truncated reverse scan)."""
    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=1, n_storage=2, sim_seed=7)
    )
    try:
        async def body():
            txn = db.create_transaction()
            for i in range(8):
                txn.set(b"rv%02d" % i, b"v%d" % i)
            await txn.commit()

            t = db.create_transaction()
            full = await t.get_range(b"rv", b"rw")
            assert [k for k, _v in full] == [b"rv%02d" % i for i in range(8)]
            rev = await t.get_range(b"rv", b"rw", reverse=True)
            assert rev == list(reversed(full))
            fwd3 = await t.get_range(b"rv", b"rw", limit=3)
            assert [k for k, _ in fwd3] == [b"rv00", b"rv01", b"rv02"]
            rev3 = await t.get_range(b"rv", b"rw", limit=3, reverse=True)
            assert [k for k, _ in rev3] == [b"rv07", b"rv06", b"rv05"]
            # conflict narrowing: forward [begin, after(last)); reverse
            # [lowest returned, end); full scans take [begin, end)
            assert (b"rv", b"rv02\x00") in t.read_conflicts
            assert (b"rv05", b"rw") in t.read_conflicts
            assert (b"rv", b"rw") in t.read_conflicts
            # RYW: an uncommitted write and a clear merge into the scan
            t.set(b"rv03\x01", b"ryw")
            t.clear_range(b"rv06", b"rv08")
            rev4 = await t.get_range(
                b"rv", b"rw", limit=4, reverse=True, snapshot=True
            )
            assert [k for k, _ in rev4] == [
                b"rv05", b"rv04", b"rv03\x01", b"rv03",
            ]
            assert await t.get_range(b"rv", b"rw", limit=0) == []
            return True

        t = sched.spawn(body(), name="drive")
        sched.run_until(t.done)
        assert t.done.get()
    finally:
        cluster.stop()
