"""Commit-path scale-out (ISSUE 19): sequencer role, N commit proxies,
tag-partitioned tlog quorum.

Layer by layer: the SequencerRole's grant semantics (global + per-tag
version chains, duplicate replay, epoch fencing), the partitioned
TLogRole's chain wait and two-phase lock, the StorageRole's chained
applies and multi-tlog merged catch-up — then the acceptance pin: two
wire ProxyPipelines sharing one sequencer over real role processes,
with commit/abort decisions replayed against the CPU ConflictOracle in
granted-version order and exact-count consistency on both front doors.
"""

import asyncio

import pytest

from foundationdb_tpu.cluster import multiprocess as mp
from foundationdb_tpu.models.types import CommitTransaction
from foundationdb_tpu.testing.oracle import (
    COMMITTED,
    ConflictOracle,
    OracleTxn,
)
from foundationdb_tpu.wire import transport
from foundationdb_tpu.wire.codec import Mutation


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# SequencerRole: version-batch allotment semantics


def test_sequencer_grants_chain_globally_and_per_tag():
    async def scenario():
        seq = mp.SequencerRole(recovery_version=100, n_tags=2)
        g1 = await seq.get_commit_version(mp.GetCommitVersionRequest(
            proxy_id="proxy0", request_num=1, most_recent_processed=0,
            epoch=0, tags=[0],
        ))
        # the first grant chains off the recovery version on both the
        # global chain and its declared tag's chain
        assert g1.prev_version == 100
        assert g1.version > g1.prev_version
        assert list(g1.tag_prevs) == [100]
        g2 = await seq.get_commit_version(mp.GetCommitVersionRequest(
            proxy_id="proxy1", request_num=1, most_recent_processed=0,
            epoch=0, tags=[0, 1],
        ))
        # global chain: proxy1's grant chains off proxy0's version
        assert g2.prev_version == g1.version
        # tag 0 last saw g1; tag 1 has never been granted
        assert list(g2.tag_prevs) == [g1.version, 100]
        g3 = await seq.get_commit_version(mp.GetCommitVersionRequest(
            proxy_id="proxy0", request_num=2, most_recent_processed=1,
            epoch=0, tags=[1],
        ))
        assert g3.prev_version == g2.version
        assert list(g3.tag_prevs) == [g2.version]
        # duplicate request: the SAME grant replays, including tag_prevs
        dup = await seq.get_commit_version(mp.GetCommitVersionRequest(
            proxy_id="proxy0", request_num=2, most_recent_processed=1,
            epoch=0, tags=[1],
        ))
        assert (dup.version, dup.prev_version, list(dup.tag_prevs)) == (
            g3.version, g3.prev_version, list(g3.tag_prevs)
        )
        assert seq.grants == 3  # the replay is not a fresh grant

    run(scenario())


def test_sequencer_fences_stale_epochs():
    async def scenario():
        seq = mp.SequencerRole(epoch=5)
        with pytest.raises(transport.RemoteError):
            await seq.get_commit_version(mp.GetCommitVersionRequest(
                proxy_id="proxy0", request_num=1, most_recent_processed=0,
                epoch=4, tags=[0],
            ))
        with pytest.raises(transport.RemoteError):
            await seq.report_committed(
                mp.ReportRawCommittedVersionRequest(version=7, epoch=4)
            )

    run(scenario())


def test_sequencer_live_committed_feeds_grv():
    async def scenario():
        seq = mp.SequencerRole(recovery_version=50)
        rep = await seq.report_committed(
            mp.ReportRawCommittedVersionRequest(version=-1, epoch=0)
        )
        assert rep.live_version == 50  # starts at the recovery version
        await seq.report_committed(
            mp.ReportRawCommittedVersionRequest(version=90, epoch=0)
        )
        rep = await seq.report_committed(
            mp.ReportRawCommittedVersionRequest(version=-1, epoch=0)
        )
        assert rep.live_version == 90

    run(scenario())


# ---------------------------------------------------------------------------
# TLogRole: the per-tag chain wait + two-phase recovery lock


def test_partitioned_tlog_parks_until_predecessor_lands():
    async def scenario():
        tlog = mp.TLogRole(partitioned=True)
        await tlog.lock(mp.TLogLock(epoch=0, recovery_version=0,
                                    partitioned=1))
        order = []

        async def late_push():
            rep = await tlog.push(mp.TLogPush(
                version=10, prev_version=5,
                mutations=[Mutation(0, b"b", b"2")], epoch=0,
            ))
            order.append(("late", rep.durable_version))

        task = asyncio.ensure_future(late_push())
        await asyncio.sleep(0.05)
        assert not task.done()  # parked: version 5 hasn't landed
        assert tlog._chain_waiters == 1
        rep = await tlog.push(mp.TLogPush(
            version=5, prev_version=0,
            mutations=[Mutation(0, b"a", b"1")], epoch=0,
        ))
        order.append(("early", rep.durable_version))
        await task
        assert order == [("early", 5), ("late", 10)]
        assert [v for v, _m in tlog.entries] == [5, 10]

    run(scenario())


def test_partitioned_tlog_lock_drains_parked_waiters_as_stale():
    async def scenario():
        tlog = mp.TLogRole(partitioned=True)
        await tlog.lock(mp.TLogLock(epoch=1, recovery_version=0,
                                    partitioned=1))

        async def doomed_push():
            await tlog.push(mp.TLogPush(
                version=100, prev_version=99,
                mutations=[], epoch=1,
            ))

        task = asyncio.ensure_future(doomed_push())
        await asyncio.sleep(0.05)
        assert not task.done()
        # phase-two lock of the NEXT generation: the floor advances and
        # the parked waiter drains as a stale-epoch reject, not a wedge
        await tlog.lock(mp.TLogLock(epoch=2, recovery_version=120,
                                    partitioned=1))
        with pytest.raises(transport.RemoteError):
            await task
        assert tlog.version == 120
        # the lock turned the chain-wait flag on for survivors too
        surv = mp.TLogRole()
        assert not surv.partitioned
        await surv.lock(mp.TLogLock(epoch=1, recovery_version=0,
                                    partitioned=1))
        assert surv.partitioned

    run(scenario())


# ---------------------------------------------------------------------------
# StorageRole: chained applies + merged multi-tlog catch-up


def test_storage_chained_applies_order_interleaved_appliers():
    async def scenario():
        st = mp.StorageRole()
        done = []

        async def late_apply():
            await st.apply_batch(mp.StorageApplyBatch(
                versions=[20], groups=[[Mutation(0, b"k", b"late")]],
                prev_versions=[10],
            ))
            done.append("late")

        task = asyncio.ensure_future(late_apply())
        await asyncio.sleep(0.05)
        assert not task.done()  # parked on prev 10
        await st.apply_batch(mp.StorageApplyBatch(
            versions=[10], groups=[[Mutation(0, b"k", b"early")]],
            prev_versions=[0],
        ))
        done.append("early")
        await task
        assert done == ["early", "late"]
        assert st.version == 20
        assert st.history[b"k"] == [(10, b"early"), (20, b"late")]
        # contiguous runs inside one batch wait once, then sweep
        await st.apply_batch(mp.StorageApplyBatch(
            versions=[30, 40], groups=[[], [Mutation(0, b"k", b"v40")]],
            prev_versions=[20, 30],
        ))
        assert st.version == 40

    run(scenario())


def test_storage_advance_floor_unblocks_post_recovery_chain():
    async def scenario():
        st = mp.StorageRole()

        async def first_new_gen_apply():
            await st.apply_batch(mp.StorageApplyBatch(
                versions=[60], groups=[[Mutation(0, b"k", b"new")]],
                prev_versions=[50],
            ))

        task = asyncio.ensure_future(first_new_gen_apply())
        await asyncio.sleep(0.05)
        assert not task.done()
        await st.advance_floor(50)  # what recovery's catch-up does
        await task
        assert st.version == 60

    run(scenario())


def test_storage_merged_catchup_combines_cross_tag_versions(tmp_path):
    """A version spanning tags appears in EVERY owning tlog (with that
    tag's clipped mutations): the k-way merged catch-up must COMBINE
    same-version heads into one apply, never drop one."""
    t0 = mp.spawn_role("tlog", str(tmp_path), index=0)
    t1 = mp.spawn_role("tlog", str(tmp_path), index=1)
    try:
        async def scenario():
            c0 = await mp.connect(t0.address)
            c1 = await mp.connect(t1.address)
            # tag 0 alone at v10, BOTH tags at v20, tag 1 alone at v30
            await c0.call(mp.TOKEN_TLOG_PUSH, mp.TLogPush(
                version=10, prev_version=0,
                mutations=[Mutation(0, b"a", b"1")], epoch=0))
            await c0.call(mp.TOKEN_TLOG_PUSH, mp.TLogPush(
                version=20, prev_version=10,
                mutations=[Mutation(0, b"a", b"2")], epoch=0))
            await c1.call(mp.TOKEN_TLOG_PUSH, mp.TLogPush(
                version=20, prev_version=0,
                mutations=[Mutation(0, b"\xf0z", b"9")], epoch=0))
            await c1.call(mp.TOKEN_TLOG_PUSH, mp.TLogPush(
                version=30, prev_version=20,
                mutations=[Mutation(0, b"\xf0z", b"10")], epoch=0))
            st = mp.StorageRole()
            await st.catch_up_from_tlogs([t0.address, t1.address])
            assert st.version == 30
            assert st.history[b"a"] == [(10, b"1"), (20, b"2")]
            assert st.history[b"\xf0z"] == [(20, b"9"), (30, b"10")]
            await c0.close()
            await c1.close()

        run(scenario())
    finally:
        t0.stop()
        t1.stop()


# ---------------------------------------------------------------------------
# The acceptance pin: two proxies, one sequencer, tag-partitioned tlogs


@pytest.fixture
def scaleout_procs(tmp_path):
    procs = {
        "resolver": mp.spawn_role("resolver", str(tmp_path)),
        "tlog0": mp.spawn_role("tlog", str(tmp_path), index=0),
        "tlog1": mp.spawn_role("tlog", str(tmp_path), index=1),
        "storage": mp.spawn_role("storage", str(tmp_path)),
        "sequencer": mp.spawn_role("sequencer", str(tmp_path)),
    }
    yield procs
    for p in procs.values():
        p.stop()


async def _scaleout_pipeline(procs, proxy_id):
    """One in-process ProxyPipeline wired like the controller recruits
    a scale-out proxy: shared sequencer, tag-partitioned tlogs."""
    conns = [
        await mp.connect(procs["resolver"].address),
        await mp.connect(procs["tlog0"].address),
        await mp.connect(procs["tlog1"].address),
        await mp.connect(procs["storage"].address),
        await mp.connect(procs["sequencer"].address),
    ]
    resolver, tl0, tl1, storage, seq = conns
    pipe = mp.ProxyPipeline(
        [resolver], tl0, storage,
        sequencer=seq, proxy_id=proxy_id,
        tlogs=[tl0, tl1], tlog_boundaries=[b"\x80"],
        batch_interval=0.001,
    )
    pipe.start()
    return pipe, conns


def test_two_proxies_share_the_version_chain_with_oracle_parity(
    scaleout_procs,
):
    n_clients, n_ops, n_keys = 6, 10, 4
    # counter keys on BOTH sides of the 0x80 tag boundary
    keys = [b"ctr%d" % i for i in range(n_keys // 2)] + [
        b"\xf0ctr%d" % i for i in range(n_keys - n_keys // 2)
    ]

    async def scenario():
        # the two-phase lock the controller's recovery walk runs: arm
        # the chain wait and set the per-tag floor before any push
        for name in ("tlog0", "tlog1"):
            c = await mp.connect(scaleout_procs[name].address)
            await c.call(mp.TOKEN_TLOG_LOCK, mp.TLogLock(
                epoch=0, recovery_version=0, partitioned=1))
            await c.close()
        # ... and the resolver priming batch: boots the version chain
        # at the recovery version so the first grant's prev resolves
        c = await mp.connect(scaleout_procs["resolver"].address)
        await c.call(mp.TOKEN_RESOLVE, mp.ResolveTransactionBatchRequest(
            prev_version=-1, version=0, last_received_version=-1, epoch=0))
        await c.close()
        pipe_a, conns_a = await _scaleout_pipeline(scaleout_procs, "proxy0")
        pipe_b, conns_b = await _scaleout_pipeline(scaleout_procs, "proxy1")
        pipes = [pipe_a, pipe_b]
        committed = {k: 0 for k in keys}
        records = []  # (key, snapshot, outcome_version | None)

        async def client(cid):
            pipe = pipes[cid % 2]
            for i in range(n_ops):
                key = keys[(cid + i) % n_keys]
                kr = (key, key + b"\x00")
                rv = await pipe.get_read_version()
                cur = await pipe.read(key, rv)
                n = int.from_bytes(cur or b"\0" * 8, "little")
                try:
                    v = await pipe.commit(CommitTransaction(
                        read_conflict_ranges=[kr],
                        write_conflict_ranges=[kr],
                        read_snapshot=rv,
                        mutations=[Mutation(
                            0, key, (n + 1).to_bytes(8, "little")
                        )],
                    ))
                except mp.NotCommittedError:
                    records.append((key, rv, None))
                    continue
                committed[key] += 1
                records.append((key, rv, v))
                # cross-proxy visibility: a GRV issued on the OTHER
                # proxy after this ack must observe the commit
                other = pipes[(cid + 1) % 2]
                assert await other.get_read_version() >= v

        await asyncio.gather(*(client(c) for c in range(n_clients)))
        assert sum(committed.values()) > 0
        # both proxies really ran on the shared chain
        assert pipe_a.version_grants > 0 and pipe_b.version_grants > 0
        assert pipe_a.saturation()["tag_partitioned"]

        # -- exact-count consistency through BOTH front doors ---------
        for pipe in pipes:
            rv = await pipe.get_read_version()
            for key in keys:
                cur = await pipe.read(key, rv)
                n = int.from_bytes(cur or b"\0" * 8, "little")
                assert n == committed[key], (
                    f"{key!r}: {n} != {committed[key]} committed"
                )

        # -- decision parity vs the CPU oracle in granted order -------
        # Replay every COMMITTED txn in commit-version order (the
        # global chain is the single-proxy order): the oracle must
        # agree each one commits — interleaved proxy batches resolved
        # exactly as the serial order would.
        oracle = ConflictOracle()
        commits = sorted(
            (v, key, rv) for key, rv, v in records if v is not None
        )
        by_version: dict[int, list] = {}
        for v, key, rv in commits:
            by_version.setdefault(v, []).append((key, rv))
        for v in sorted(by_version):
            # txns batched by one proxy share a commit version: replay
            # the whole batch in one oracle step, like the resolver saw
            txns = [OracleTxn(
                read_conflict_ranges=[(key, key + b"\x00")],
                write_conflict_ranges=[(key, key + b"\x00")],
                read_snapshot=rv,
            ) for key, rv in by_version[v]]
            res = oracle.resolve(txns, v)
            assert res.verdicts == [COMMITTED] * len(txns), (
                f"oracle aborts committed txn at v={v}: {res.verdicts}"
            )
        # every abort was a REAL conflict: a committed write on the
        # same key landed after the aborted txn's snapshot
        for key, rv, v in records:
            if v is not None:
                continue
            assert any(
                cv > rv and ck == key for cv, ck, _r in commits
            ), f"spurious abort: key={key!r} snapshot={rv}"

        # -- tag partitioning: each tlog holds ONLY its tag's keys ----
        for name, lo, hi in (("tlog0", b"", b"\x80"),
                             ("tlog1", b"\x80", None)):
            c = await mp.connect(scaleout_procs[name].address)
            rep = await c.call(mp.TOKEN_TLOG_PEEK_BATCH,
                               mp.TLogPeekBatchReq(after_version=0,
                                                   max_entries=10000))
            assert rep.versions, f"{name} saw no pushes"
            for muts in rep.groups:
                for m in muts:
                    assert m.param1 >= lo
                    if hi is not None:
                        assert m.param1 < hi
            await c.close()

        for pipe, conns in ((pipe_a, conns_a), (pipe_b, conns_b)):
            await pipe.stop()
            for c in conns:
                await c.close()

    run(scenario())


def test_scaleout_worker_hosts_sequencer_and_partitioned_tlog(tmp_path):
    """The controller's recruitment path: a WorkerRole builds the
    sequencer and a partitioned tlog from InitializeRole specs."""
    import json

    async def scenario():
        worker = mp.WorkerRole("w0", str(tmp_path / "w0.sock"))
        rep = await worker.init_role(mp.InitializeRole(payload=json.dumps({
            "kind": "sequencer", "epoch": 3, "recovery_version": 500,
            "n_tags": 2,
        })))
        info = json.loads(rep.payload)
        assert info["version"] == 500
        seq = worker.roles["sequencer"]
        assert seq.epoch == 3 and seq.n_tags == 2
        rep = await worker.init_role(mp.InitializeRole(payload=json.dumps({
            "kind": "tlog", "epoch": 3, "partitioned": True,
        })))
        assert worker.roles["tlog"].partitioned

    run(scenario())
