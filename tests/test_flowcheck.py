"""flowcheck: every rule family exercised on fixtures, plus the live
tree self-check (zero non-baselined violations — the CI gate contract).

Fixture snippets are linted through `analyze_source`, which runs the
file-level rules as if the snippet lived at a chosen path — the path is
what selects scope (sim-schedulable vs kernel vs out-of-scope), so the
same snippet can assert both the positive and the scope-negative case.
"""

from pathlib import Path

import pytest

from foundationdb_tpu.analysis import analyze_source, run_analysis
from foundationdb_tpu.analysis.manifest import load_manifest
from foundationdb_tpu.analysis.rules_probes import (
    check_probe_ledger,
    tree_manifest,
)
from foundationdb_tpu.analysis.walker import FileContext

SIM = "foundationdb_tpu/cluster/_snippet.py"
OPS = "foundationdb_tpu/ops/_snippet.py"
OUT = "foundationdb_tpu/wire/_snippet.py"  # outside every scope

REPO = Path(__file__).resolve().parents[1]


def rules_of(findings):
    return [f.rule for f in findings]


# -- determinism family ----------------------------------------------------


def test_wall_clock_flagged_in_sim_scope():
    src = "import time\n\ndef f():\n    return time.time()\n"
    assert rules_of(analyze_source(src, SIM)) == ["determinism.wall-clock"]
    # aliased import still resolves
    src2 = "import time as _t\n\ndef f():\n    _t.sleep(1)\n"
    assert rules_of(analyze_source(src2, SIM)) == ["determinism.wall-clock"]
    # from-import too
    src3 = "from time import monotonic\n\ndef f():\n    return monotonic()\n"
    assert rules_of(analyze_source(src3, SIM)) == ["determinism.wall-clock"]


def test_wall_clock_out_of_scope_and_negative():
    src = "import time\n\ndef f():\n    return time.time()\n"
    assert analyze_source(src, OUT) == []  # wire/ is the real-I/O side
    ok = "def f(sched):\n    return sched.now()\n"
    assert analyze_source(ok, SIM) == []


def test_datetime_now_flagged():
    src = (
        "import datetime\n\ndef f():\n"
        "    return datetime.datetime.now()\n"
    )
    assert rules_of(analyze_source(src, SIM)) == ["determinism.wall-clock"]
    # dot-boundary: a sim-clock wrapper merely NAMED *datetime is fine
    ok = "def f(start_datetime):\n    return start_datetime.now()\n"
    assert analyze_source(ok, SIM) == []


def test_unseeded_random_flagged():
    src = (
        "import os, random\nimport numpy as np\n\ndef f():\n"
        "    a = os.urandom(8)\n"
        "    b = random.random()\n"
        "    c = np.random.rand(3)\n"
        "    d = np.random.default_rng(0)\n"  # seeded: NOT flagged
        "    return a, b, c, d\n"
    )
    assert rules_of(analyze_source(src, SIM)) == [
        "determinism.unseeded-random"
    ] * 3


def test_asyncio_flagged_in_sim_scope():
    src = "import asyncio\n\nasync def f():\n    await asyncio.sleep(1)\n"
    got = rules_of(analyze_source(src, SIM))
    assert got == ["determinism.asyncio"] * 2  # import + call
    assert analyze_source(src, OUT) == []


def test_suppression_comment_absorbs_the_finding():
    src = (
        "import time\n\ndef f():\n"
        "    return time.time()  # flowcheck: ignore[determinism.wall-clock]\n"
    )
    assert analyze_source(src, SIM) == []
    # family-level and bare ignores work too
    fam = (
        "import time\n\ndef f():\n"
        "    return time.time()  # flowcheck: ignore[determinism]\n"
    )
    assert analyze_source(fam, SIM) == []
    bare = (
        "import time\n\ndef f():\n"
        "    return time.time()  # flowcheck: ignore\n"
    )
    assert analyze_source(bare, SIM) == []
    # a suppression for a DIFFERENT rule does not absorb it
    wrong = (
        "import time\n\ndef f():\n"
        "    return time.time()  # flowcheck: ignore[actor.swallow]\n"
    )
    assert rules_of(analyze_source(wrong, SIM)) == ["determinism.wall-clock"]


def test_trailing_suppression_does_not_bleed_to_next_line():
    """A justified trailing ignore on line N must not absorb an
    unrelated violation on line N+1; a STANDALONE comment line
    annotates the line below it."""
    src = (
        "import time\n\ndef f():\n"
        "    a = time.time()  # flowcheck: ignore[determinism]\n"
        "    time.sleep(1)\n"
        "    return a\n"
    )
    got = analyze_source(src, SIM)
    assert rules_of(got) == ["determinism.wall-clock"]
    assert got[0].line == 5  # the sleep, not the suppressed time()
    above = (
        "import time\n\ndef f():\n"
        "    # flowcheck: ignore[determinism]\n"
        "    return time.time()\n"
    )
    assert analyze_source(above, SIM) == []


def test_tuple_and_attribute_broad_excepts_flagged():
    """`except (Exception, ValueError): pass` and
    `except builtins.Exception: pass` must not evade actor.swallow."""
    tup = (
        "def f(x):\n    try:\n        x()\n"
        "    except (Exception, ValueError):\n        pass\n"
    )
    assert rules_of(analyze_source(tup, SIM)) == ["actor.swallow"]
    attr = (
        "import builtins\n\ndef f(x):\n    try:\n        x()\n"
        "    except builtins.Exception:\n        pass\n"
    )
    assert rules_of(analyze_source(attr, SIM)) == ["actor.swallow"]
    # a narrow tuple stays fine
    ok = (
        "def f(x):\n    try:\n        x()\n"
        "    except (KeyError, ValueError):\n        pass\n"
    )
    assert analyze_source(ok, SIM) == []


def test_suppression_inside_string_literal_is_inert():
    """Only REAL comments suppress: a string (or docstring) merely
    mentioning the marker syntax must not blind the gate."""
    src = (
        "import time\n\ndef f():\n"
        "    msg = 'add # flowcheck: ignore to silence'\n"
        "    return time.time(), msg\n"
    )
    assert rules_of(analyze_source(src, SIM)) == ["determinism.wall-clock"]
    # marker in a string ON the offending line: still inert
    same_line = (
        "import time\n\ndef f():\n"
        "    return time.time(), '# flowcheck: ignore'\n"
    )
    assert rules_of(analyze_source(same_line, SIM)) == [
        "determinism.wall-clock"
    ]


# -- actor-safety family ---------------------------------------------------


def test_fire_and_forget_spawn_flagged():
    src = "def f(sched, coro):\n    sched.spawn(coro)\n"
    assert rules_of(analyze_source(src, SIM)) == ["actor.fire-and-forget"]
    ok = "def f(sched, coro):\n    t = sched.spawn(coro)\n    return t\n"
    assert analyze_source(ok, SIM) == []
    sup = (
        "def f(sched, coro):\n"
        "    sched.spawn(coro)  # flowcheck: ignore[actor.fire-and-forget]\n"
    )
    assert analyze_source(sup, SIM) == []


def test_unawaited_future_flagged():
    src = "async def f(sched):\n    sched.delay(1.0)\n"
    assert rules_of(analyze_source(src, SIM)) == ["actor.unawaited-future"]
    ok = "async def f(sched):\n    await sched.delay(1.0)\n"
    assert analyze_source(ok, SIM) == []


def test_bare_local_coroutine_call_flagged():
    src = (
        "async def worker():\n    pass\n\n"
        "def f():\n    worker()\n"
    )
    assert rules_of(analyze_source(src, SIM)) == ["actor.unawaited-future"]


def test_broad_swallow_flagged():
    src = (
        "def f(x):\n    try:\n        x()\n"
        "    except Exception:\n        pass\n"
    )
    assert rules_of(analyze_source(src, SIM)) == ["actor.swallow"]
    bare = (
        "def f(x):\n    try:\n        x()\n"
        "    except:\n        pass\n"
    )
    assert rules_of(analyze_source(bare, SIM)) == ["actor.swallow"]
    # narrow type or a body that DOES something: fine
    ok = (
        "def f(x, log):\n    try:\n        x()\n"
        "    except KeyError:\n        pass\n"
        "    try:\n        x()\n"
        "    except Exception as e:\n        log(e)\n"
    )
    assert analyze_source(ok, SIM) == []


# -- JAX hazard family -----------------------------------------------------


def test_host_sync_flagged_in_kernel_scope():
    src = "def f(x):\n    return float(x)\n"
    assert rules_of(analyze_source(src, OPS)) == ["jax.host-sync"]
    assert analyze_source(src, SIM) == []  # kernel scope only
    ok = "def f():\n    return float(1.5)\n"  # literal: static
    assert analyze_source(ok, OPS) == []
    item = "def f(x):\n    return x.item()\n"
    assert rules_of(analyze_source(item, OPS)) == ["jax.host-sync"]


def test_host_numpy_flagged_in_kernel_scope():
    src = (
        "import numpy as np\n\ndef f(a, b):\n"
        "    return np.maximum(a, b)\n"
    )
    assert rules_of(analyze_source(src, OPS)) == ["jax.host-numpy"]
    # exactly ONE finding per call: np.nonzero is host-numpy, not also
    # double-reported as data-dep-shape
    dd = (
        "import numpy as np\n\ndef f(x):\n"
        "    return np.nonzero(x)\n"
    )
    assert rules_of(analyze_source(dd, OPS)) == ["jax.host-numpy"]
    ok = (
        "import jax.numpy as jnp\n\ndef f(a, b):\n"
        "    return jnp.maximum(a, b)\n"
    )
    assert analyze_source(ok, OPS) == []


def test_data_dependent_shape_flagged():
    src = (
        "import jax.numpy as jnp\n\ndef f(x):\n"
        "    return jnp.nonzero(x)\n"
    )
    assert rules_of(analyze_source(src, OPS)) == ["jax.data-dep-shape"]
    one_arg = (
        "import jax.numpy as jnp\n\ndef f(x):\n"
        "    return jnp.where(x)\n"
    )
    assert rules_of(analyze_source(one_arg, OPS)) == ["jax.data-dep-shape"]
    ok = (
        "import jax.numpy as jnp\n\ndef f(c, a, b):\n"
        "    return jnp.where(c, a, b)\n"
    )
    assert analyze_source(ok, OPS) == []


def test_block_until_ready_in_loop_flagged_everywhere():
    src = (
        "def f(outs):\n    for o in outs:\n"
        "        o.block_until_ready()\n"
    )
    # package-wide rule: fires even outside kernel scope
    assert rules_of(analyze_source(src, OUT)) == ["jax.block-in-loop"]
    ok = (
        "def f(outs):\n    outs[-1].block_until_ready()\n"
    )
    assert analyze_source(ok, OUT) == []


# -- probe accounting family (tree checks) ---------------------------------


def ctxs_from(*sources):
    return [
        FileContext(f"foundationdb_tpu/cluster/_fix{i}.py", src)
        for i, src in enumerate(sources)
    ]


def test_undeclared_probe_flagged(tmp_path):
    man = tmp_path / "m.json"
    ctxs = ctxs_from(
        "from foundationdb_tpu.utils.probes import code_probe\n"
        "def f():\n    code_probe(True, 'x.y')\n"
    )
    got = [f.rule for f in check_probe_ledger(ctxs, manifest_path=man)]
    assert "probe.undeclared" in got


def test_duplicate_declare_flagged(tmp_path):
    man = tmp_path / "m.json"
    ctxs = ctxs_from(
        "from foundationdb_tpu.utils.probes import declare\n"
        "declare('dup.probe')\n",
        "from foundationdb_tpu.utils.probes import declare\n"
        "declare('dup.probe')\n",
    )
    got = [f.rule for f in check_probe_ledger(ctxs, manifest_path=man)]
    assert "probe.duplicate" in got


def test_dynamic_probe_name_flagged(tmp_path):
    man = tmp_path / "m.json"
    ctxs = ctxs_from(
        "from foundationdb_tpu.utils.probes import code_probe\n"
        "def f(name):\n    code_probe(True, name)\n"
    )
    got = [f.rule for f in check_probe_ledger(ctxs, manifest_path=man)]
    assert "probe.dynamic-name" in got


def test_keyword_probe_name_is_accounted(tmp_path):
    """code_probe(cond, name='x.y') must not slip past the ledger."""
    man = tmp_path / "m.json"
    ctxs = ctxs_from(
        "from foundationdb_tpu.utils.probes import code_probe\n"
        "def f():\n    code_probe(True, name='kw.probe')\n"
    )
    got = [f.rule for f in check_probe_ledger(ctxs, manifest_path=man)]
    assert "probe.undeclared" in got
    # non-literal keyword name is dynamic, not invisible
    ctxs2 = ctxs_from(
        "from foundationdb_tpu.utils.probes import code_probe\n"
        "def f(n):\n    code_probe(True, name=n)\n"
    )
    got2 = [f.rule for f in check_probe_ledger(ctxs2, manifest_path=man)]
    assert "probe.dynamic-name" in got2


def test_manifest_drift_flagged(tmp_path):
    man = tmp_path / "m.json"  # missing file = empty manifest
    ctxs = ctxs_from(
        "from foundationdb_tpu.utils.probes import declare, code_probe\n"
        "declare('a.b')\n"
        "def f():\n    code_probe(True, 'a.b')\n"
    )
    got = [f.rule for f in check_probe_ledger(ctxs, manifest_path=man)]
    assert got == ["probe.manifest-drift"]


# -- trace-event accounting family (tree checks) ----------------------------


def _trace_rules(ctxs, tmp_path, register=True):
    """Run the trace ledger check; with register=True the fixture's own
    events are pre-registered so only NON-drift findings surface."""
    from foundationdb_tpu.analysis.manifest import save_trace_manifest
    from foundationdb_tpu.analysis.rules_trace import (
        check_trace_ledger,
        tree_trace_manifest,
    )

    man = tmp_path / "tm.json"
    if register:
        save_trace_manifest(tree_trace_manifest(ctxs), path=man)
    return [
        f.rule for f in check_trace_ledger(ctxs, manifest_path=man)
    ]


def test_trace_lowercase_event_flagged(tmp_path):
    ctxs = ctxs_from(
        "from foundationdb_tpu.utils.trace import TraceEvent\n"
        "def f():\n    TraceEvent('badName').log()\n"
    )
    assert "trace.lowercase-event" in _trace_rules(ctxs, tmp_path)
    ok = ctxs_from(
        "from foundationdb_tpu.utils.trace import TraceEvent\n"
        "def f():\n    TraceEvent('GoodName').log()\n"
    )
    assert _trace_rules(ok, tmp_path) == []


def test_trace_dynamic_event_flagged(tmp_path):
    ctxs = ctxs_from(
        "from foundationdb_tpu.utils.trace import TraceEvent\n"
        "def f(name):\n    TraceEvent(name).log()\n"
    )
    assert "trace.dynamic-name" in _trace_rules(ctxs, tmp_path)


def test_trace_detail_case_flagged(tmp_path):
    ctxs = ctxs_from(
        "from foundationdb_tpu.utils.trace import TraceEvent\n"
        "def f():\n    TraceEvent('Ev').detail('bad_key', 1).log()\n"
    )
    assert "trace.detail-case" in _trace_rules(ctxs, tmp_path)
    ok = ctxs_from(
        "from foundationdb_tpu.utils.trace import TraceEvent\n"
        "def f():\n    TraceEvent('Ev').detail('GoodKey', 1).log()\n"
    )
    assert _trace_rules(ok, tmp_path) == []


def test_trace_detail_case_is_anchored_to_trace_events(tmp_path):
    """Only .detail() on a TraceEvent chain is the trace schema's
    business: an unrelated object's .detail() API must not gate-fail,
    while name-bound and with-bound TraceEvents are still covered."""
    unrelated = ctxs_from(
        "def f(err):\n    err.detail('shard_id', 1)\n"
    )
    assert _trace_rules(unrelated, tmp_path) == []
    bound = ctxs_from(
        "from foundationdb_tpu.utils.trace import TraceEvent\n"
        "def f():\n"
        "    ev = TraceEvent('Ev')\n"
        "    ev.detail('bad_key', 1)\n"
        "    ev.log()\n"
    )
    assert "trace.detail-case" in _trace_rules(bound, tmp_path)
    with_bound = ctxs_from(
        "from foundationdb_tpu.utils.trace import TraceEvent\n"
        "def f():\n"
        "    with TraceEvent('Ev') as e:\n"
        "        e.detail('bad_key', 1)\n"
    )
    assert "trace.detail-case" in _trace_rules(with_bound, tmp_path)


def test_trace_batch_names_accounted(tmp_path):
    """g_trace_batch.add_event/add_attach NAME args join the event
    schema (they render as TraceLog Types) — casing enforced, manifest
    tracked."""
    from foundationdb_tpu.analysis.rules_trace import tree_trace_manifest

    ctxs = ctxs_from(
        "from foundationdb_tpu.utils import trace\n"
        "def f(d):\n"
        "    trace.g_trace_batch.add_event('commitDebug', d, 'X.Before')\n"
    )
    assert "trace.lowercase-event" in _trace_rules(ctxs, tmp_path)
    ok = ctxs_from(
        "from foundationdb_tpu.utils import trace\n"
        "def f(d):\n"
        "    trace.g_trace_batch.add_event('CommitDebug', d, 'X.Before')\n"
        "    trace.g_trace_batch.add_attach('CommitAttachID', d, 'b1')\n"
    )
    assert _trace_rules(ok, tmp_path) == []
    assert set(tree_trace_manifest(ok)) == {"CommitDebug", "CommitAttachID"}


def test_trace_manifest_drift_flagged(tmp_path):
    ctxs = ctxs_from(
        "from foundationdb_tpu.utils.trace import TraceEvent\n"
        "def f():\n    TraceEvent('NewEvent').log()\n"
    )
    got = _trace_rules(ctxs, tmp_path, register=False)
    assert got == ["trace.manifest-drift"]


def test_live_tree_trace_manifest_is_current():
    from foundationdb_tpu.analysis.manifest import load_trace_manifest
    from foundationdb_tpu.analysis.rules_trace import tree_trace_manifest

    result = run_analysis(root=REPO)
    assert tree_trace_manifest(result.contexts) == load_trace_manifest(), (
        "trace_manifest.json is stale: run `python -m "
        "foundationdb_tpu.analysis --write-trace-manifest`"
    )


# -- the live tree: the actual gate ----------------------------------------


def test_live_tree_has_zero_new_violations():
    """`python -m foundationdb_tpu.analysis` exit-0 equivalent: the
    tree, checked against the shipped baseline, is clean — and the
    baseline itself has no stale (already-fixed) entries."""
    result = run_analysis(root=REPO)
    assert result.ok, "NEW flowcheck violations:\n" + "\n".join(
        f.render() for f in result.new
    )
    assert not result.stale, (
        "baseline entries no longer match any finding (fixed code? "
        f"run --write-baseline): {dict(result.stale)}"
    )


def test_live_tree_manifest_is_current():
    result = run_analysis(root=REPO)
    assert tree_manifest(result.contexts) == load_manifest(), (
        "probe_manifest.json is stale: run `python -m "
        "foundationdb_tpu.analysis --write-manifest`"
    )


def test_rule_catalog_is_populated():
    from foundationdb_tpu.analysis import registry

    registry.load_rules()
    families = {r.family for r in registry.RULES.values()}
    assert {"determinism", "actor", "jax", "probe", "wire"} <= families
    assert len(registry.RULES) >= 19


def test_cli_entrypoint_exits_zero():
    """The exact command scripts/check.sh and CI run."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "foundationdb_tpu.analysis"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 new" in proc.stdout


# -- flow family (stale state across a wait; analysis/cfg.py dataflow) ------


def test_stale_guard_across_wait_flagged_and_fixed_shape_clean():
    """The storage.py bug class: a request validated against mutable
    shared state, awaited past, never re-validated. The PR-2 fix shape
    (re-read + re-raise after the wait) must be clean."""
    old = (
        "class SS:\n"
        "    def _gc(self, floor):\n"
        "        self.oldest_version = floor\n"
        "    async def _wait_for_version(self, version):\n"
        "        if version < self.oldest_version:\n"
        "            raise ValueError(version)\n"
        "        await self.version.when_at_least(version)\n"
    )
    got = analyze_source(old, SIM)
    assert rules_of(got) == ["flow.stale-read-across-wait"]
    assert got[0].line == 5  # the guard, where the fix belongs
    fixed = old + (
        "        if version < self.oldest_version:\n"
        "            raise ValueError(version)\n"
    )
    assert analyze_source(fixed, SIM) == []


def test_reintroducing_storage_stale_floor_read_is_caught():
    """THE acceptance pin: surgically revert the PR-2 fix in the REAL
    cluster/storage.py (drop the post-wait floor re-validation) and the
    gate must catch it as flow.stale-read-across-wait; the shipped file
    stays clean. If storage.py's read path is ever refactored out from
    under this surgery, fail loudly rather than silently un-pin."""
    src = (REPO / "foundationdb_tpu/cluster/storage.py").read_text()
    marker = "await self.version.when_at_least(version)"
    assert marker in src, "storage.py _wait_for_version moved: re-pin"
    tail = src.index(marker) + len(marker)
    recheck_end = src.index("raise TransactionTooOld(version)", tail)
    recheck_end = src.index("\n", recheck_end)
    reverted = src[:tail] + src[recheck_end:]
    path = "foundationdb_tpu/cluster/storage.py"
    assert analyze_source(src, path) == []  # shipped file: clean
    got = analyze_source(reverted, path)
    assert "flow.stale-read-across-wait" in rules_of(got), (
        "the reverted stale-floor read escaped the gate:\n"
        + "\n".join(f.render() for f in got)
    )


def test_rmw_across_wait_flagged():
    src = (
        "class C:\n"
        "    def bump(self):\n"
        "        self.n = 1\n"
        "    async def racy(self, sched):\n"
        "        v = self.n\n"
        "        await sched.delay(0.1)\n"
        "        self.n = v + 1\n"
    )
    got = analyze_source(src, SIM)
    assert rules_of(got) == ["flow.rmw-across-wait"]
    assert got[0].line == 7  # the lossy write
    # re-reading after the wait is the fix
    ok = src.replace(
        "        self.n = v + 1\n",
        "        v = self.n\n        self.n = v + 1\n",
    )
    assert analyze_source(ok, SIM) == []


def test_one_statement_rmw_forms_flagged():
    """`self.x = await f(self.x)` and `self.x += await f()` both split
    a read-modify-write across a yield point inside one statement."""
    a = (
        "class C:\n"
        "    def bump(self):\n"
        "        self.x = 1\n"
        "    async def f(self, svc):\n"
        "        self.x = await svc.next(self.x)\n"
    )
    assert rules_of(analyze_source(a, SIM)) == ["flow.rmw-across-wait"]
    b = (
        "class C:\n"
        "    def bump(self):\n"
        "        self.x = 1\n"
        "    async def f(self, svc):\n"
        "        self.x += await svc.next()\n"
    )
    assert rules_of(analyze_source(b, SIM)) == ["flow.rmw-across-wait"]
    # consecutive statements are NOT one statement: read for logging,
    # then an unrelated fresh write, is not an RMW
    c = (
        "class C:\n"
        "    def bump(self):\n"
        "        self.x = 1\n"
        "    async def f(self, svc, log):\n"
        "        log(self.x)\n"
        "        await svc.pause()\n"
        "        self.x = 0\n"
    )
    assert analyze_source(c, SIM) == []


def test_guard_not_rechecked_check_calls():
    """The double-_check_shard_floor discipline: an invariant-check
    call taking a request parameter, awaited past, must repeat."""
    bad = (
        "class C:\n"
        "    def poke(self):\n"
        "        self.floor = 1\n"
        "    def _check_bounds(self, lo, hi, version):\n"
        "        pass\n"
        "    async def read(self, lo, hi, version, sched):\n"
        "        self._check_bounds(lo, hi, version)\n"
        "        await sched.delay(0.1)\n"
        "        return self.data\n"
    )
    got = analyze_source(bad, SIM)
    assert rules_of(got) == ["flow.guard-not-rechecked"]
    ok = bad.replace(
        "        return self.data\n",
        "        self._check_bounds(lo, hi, version)\n"
        "        return self.data\n",
    )
    assert analyze_source(ok, SIM) == []
    # a check over pure locals (not request parameters) is out of scope
    local_only = (
        "class C:\n"
        "    def poke(self):\n"
        "        self.floor = 1\n"
        "    def _check_rows(self, rows):\n"
        "        pass\n"
        "    async def read(self, sched):\n"
        "        rows = [1]\n"
        "        self._check_rows(rows)\n"
        "        await sched.delay(0.1)\n"
        "        return rows\n"
    )
    assert analyze_source(local_only, SIM) == []


def test_assert_subject_awaited_past():
    src = (
        "class C:\n"
        "    def poke(self):\n"
        "        self.hi = 9\n"
        "    async def f(self, v, sched):\n"
        "        assert v < self.hi\n"
        "        await sched.delay(0.1)\n"
        "        return v\n"
    )
    got = analyze_source(src, SIM)
    assert rules_of(got) == ["flow.guard-not-rechecked"]
    ok = src.replace(
        "        return v\n",
        "        assert v < self.hi\n        return v\n",
    )
    assert analyze_source(ok, SIM) == []


def test_snapshot_local_guarding_after_wait():
    """A local snapshot of shared state used as a guard after a wait is
    stale; dereferencing an ALIAS (attr access through it) is a live
    read and stays clean, as does a snapshot taken FROM an awaited call
    (fresh as of its own yield point)."""
    bad = (
        "class C:\n"
        "    def poke(self):\n"
        "        self.live = 1\n"
        "    async def f(self, sched, act):\n"
        "        up = self.live\n"
        "        await sched.delay(0.1)\n"
        "        if up:\n"
        "            act()\n"
    )
    assert rules_of(analyze_source(bad, SIM)) == [
        "flow.stale-read-across-wait"
    ]
    # re-reading the source after the wait clears it
    ok = bad.replace(
        "        if up:\n",
        "        up = self.live\n        if up:\n",
    )
    assert analyze_source(ok, SIM) == []
    # `if stale or self.live:` — the same-test re-read idiom is clean
    same_test = bad.replace("        if up:\n", "        if up or self.live:\n")
    assert analyze_source(same_test, SIM) == []
    # alias deref: reads THROUGH the local are live, not snapshots
    alias = (
        "class C:\n"
        "    def poke(self):\n"
        "        self.slots = {}\n"
        "    async def f(self, pid, sched, act):\n"
        "        st = self.slots.setdefault(pid, object())\n"
        "        await sched.delay(0.1)\n"
        "        if st.ready:\n"
        "            act()\n"
    )
    assert analyze_source(alias, SIM) == []
    # value born AT a yield point (await in the RHS) is not a pre-wait
    # snapshot
    fresh = (
        "class C:\n"
        "    def poke(self):\n"
        "        self.v = 1\n"
        "    async def f(self, src, sched, act):\n"
        "        items = await src.peek(self.v)\n"
        "        await sched.delay(0.1)\n"
        "        if items:\n"
        "            act()\n"
    )
    assert analyze_source(fresh, SIM) == []


def test_flow_rules_scope_and_suppression():
    src = (
        "class C:\n"
        "    def bump(self):\n"
        "        self.n = 1\n"
        "    async def racy(self, sched):\n"
        "        v = self.n\n"
        "        await sched.delay(0.1)\n"
        "        self.n = v + 1\n"
    )
    assert analyze_source(src, OUT) == []  # real-I/O side: out of scope
    sup = src.replace(
        "        self.n = v + 1\n",
        "        self.n = v + 1  # flowcheck: ignore[flow.rmw-across-wait]\n",
    )
    assert analyze_source(sup, SIM) == []


# -- walker blind spots (nested/decorated actors, comprehension awaits) ----


def test_nested_async_defs_are_walked():
    """The soak-workload shape: actors nested inside a driver function,
    racing on a captured mutable dict — the classic blind spot."""
    src = (
        "def run(sched):\n"
        "    state = {'n': 0}\n"
        "    async def racer():\n"
        "        v = state['n']\n"
        "        await sched.delay(0.1)\n"
        "        state['n'] = v + 1\n"
        "    return racer\n"
    )
    assert rules_of(analyze_source(src, SIM)) == ["flow.rmw-across-wait"]


def test_decorated_actors_are_walked():
    src = (
        "def actor(fn):\n"
        "    return fn\n"
        "class C:\n"
        "    def bump(self):\n"
        "        self.n = 1\n"
        "    @actor\n"
        "    async def racy(self, sched):\n"
        "        v = self.n\n"
        "        await sched.delay(0.1)\n"
        "        self.n = v + 1\n"
    )
    assert rules_of(analyze_source(src, SIM)) == ["flow.rmw-across-wait"]


def test_await_inside_comprehension_is_a_yield_point():
    """`[await f() ...]` suspends the enclosing actor per element: a
    comprehension await between read and write is still an RMW split."""
    src = (
        "class C:\n"
        "    def bump(self):\n"
        "        self.n = 1\n"
        "    async def racy(self, jobs):\n"
        "        v = self.n\n"
        "        outs = [await j.run() for j in jobs]\n"
        "        self.n = v + len(outs)\n"
    )
    assert rules_of(analyze_source(src, SIM)) == ["flow.rmw-across-wait"]


def test_async_for_and_async_with_are_yield_points():
    base = (
        "class C:\n"
        "    def bump(self):\n"
        "        self.n = 1\n"
        "    async def racy(self, stream):\n"
        "        v = self.n\n"
        "        async for _item in stream:\n"
        "            pass\n"
        "        self.n = v + 1\n"
    )
    assert "flow.rmw-across-wait" in rules_of(analyze_source(base, SIM))
    ctx = (
        "class C:\n"
        "    def bump(self):\n"
        "        self.n = 1\n"
        "    async def racy(self, lock):\n"
        "        v = self.n\n"
        "        async with lock:\n"
        "            self.n = v + 1\n"
    )
    assert "flow.rmw-across-wait" in rules_of(analyze_source(ctx, SIM))


# -- the stale-suppression audit -------------------------------------------


def test_stale_ignore_comments_are_findings(tmp_path):
    """A '# flowcheck: ignore[...]' that suppresses nothing is itself a
    finding (dead ignores must not accumulate); a LIVE ignore is not."""
    pkg = tmp_path / "foundationdb_tpu" / "cluster"
    pkg.mkdir(parents=True)
    (pkg / "fix.py").write_text(
        "import time\n"
        "def live():\n"
        "    return time.time()  # flowcheck: ignore[determinism.wall-clock]\n"
        "def dead(x):\n"
        "    return x  # flowcheck: ignore[actor.swallow]\n"
    )
    result = run_analysis(
        root=tmp_path,
        baseline_path=tmp_path / "baseline.json",
        manifest_path=tmp_path / "manifest.json",
    )
    stale = [f for f in result.new if f.rule == "flowcheck.stale-ignore"]
    assert len(stale) == 1 and stale[0].line == 5, [
        f.render() for f in result.new
    ]
    assert "actor.swallow" in stale[0].message
    # the live ignore on line 3 produced no stale finding
    assert not any(f.line == 3 for f in stale)
    # and a stale ignore FAILS the gate (it lands in result.new)
    assert not result.ok


def test_live_tree_has_no_stale_ignores():
    """Every suppression currently in the tree absorbs a real finding —
    the audit that keeps PR-era justifications from outliving their
    violations. (Subsumed by test_live_tree_has_zero_new_violations,
    pinned separately so a failure names the right contract.)"""
    result = run_analysis(root=REPO)
    stale = [
        f for f in result.findings if f.rule == "flowcheck.stale-ignore"
    ]
    assert stale == [], "\n".join(f.render() for f in stale)


def test_flow_family_in_catalog():
    from foundationdb_tpu.analysis import registry

    registry.load_rules()
    families = {r.family for r in registry.RULES.values()}
    assert "flow" in families and "flowcheck" in families
    assert {
        "flow.stale-read-across-wait", "flow.rmw-across-wait",
        "flow.guard-not-rechecked", "flowcheck.stale-ignore",
    } <= set(registry.RULES)


def test_bare_comprehension_of_coroutines_flagged():
    """`[worker() for w in ws]` as a statement builds coroutines nobody
    awaits — the comprehension variant of the bare-call blind spot."""
    src = (
        "async def worker(w):\n    pass\n\n"
        "def f(ws):\n    [worker(w) for w in ws]\n"
    )
    assert rules_of(analyze_source(src, SIM)) == ["actor.unawaited-future"]
    spawned = (
        "def f(sched, coros):\n    [sched.spawn(c) for c in coros]\n"
    )
    assert rules_of(analyze_source(spawned, SIM)) == [
        "actor.fire-and-forget"
    ]
    # keeping the results is fine
    ok = (
        "def f(sched, coros):\n"
        "    return [sched.spawn(c) for c in coros]\n"
    )
    assert analyze_source(ok, SIM) == []


def test_loop_else_runs_on_exhaustion_not_break():
    """Loop `else` lowering: the else body belongs to the EXHAUSTION
    edge only — a break path never executes it, so an else-clause
    re-read must not launder the break path's stale snapshot."""
    src = (
        "class C:\n"
        "    def poke(self):\n"
        "        self.live = 1\n"
        "    async def f(self, sched, act, cond):\n"
        "        up = self.live\n"
        "        await sched.delay(0.1)\n"
        "        while cond():\n"
        "            break\n"
        "        else:\n"
        "            up = self.live\n"
        "        if up:\n"
        "            act()\n"
    )
    assert rules_of(analyze_source(src, SIM)) == [
        "flow.stale-read-across-wait"
    ]
    # without the break, exhaustion DOES run the else: clean
    no_break = src.replace("            break\n", "            pass\n")
    assert analyze_source(no_break, SIM) == []


def test_bare_dict_comprehension_of_coroutines_flagged():
    src = (
        "async def worker(w):\n    pass\n\n"
        "def f(ws):\n    {worker(w): 1 for w in ws}\n"
    )
    assert rules_of(analyze_source(src, SIM)) == ["actor.unawaited-future"]


def test_exhaustive_match_has_no_phantom_fallthrough():
    """`case _:` always matches: the CFG must not add a no-case edge
    that bypasses every arm's re-read."""
    src = (
        "class C:\n"
        "    def poke(self):\n"
        "        self.live = 1\n"
        "    async def f(self, sched, act, x):\n"
        "        up = self.live\n"
        "        await sched.delay(0.1)\n"
        "        match x:\n"
        "            case 1:\n"
        "                up = self.live\n"
        "            case _:\n"
        "                up = self.live\n"
        "        if up:\n"
        "            act()\n"
    )
    assert analyze_source(src, SIM) == []
    # drop the wildcard arm: the no-match path is real again
    refutable = src.replace(
        "            case _:\n                up = self.live\n", ""
    )
    assert rules_of(analyze_source(refutable, SIM)) == [
        "flow.stale-read-across-wait"
    ]


def test_stale_ignores_cannot_be_baselined(tmp_path):
    """--write-baseline must not grandfather a dead ignore: the
    stale-ignore finding stays NEW even when the baseline froze it."""
    from foundationdb_tpu.analysis import baseline as baseline_mod

    pkg = tmp_path / "foundationdb_tpu" / "cluster"
    pkg.mkdir(parents=True)
    (pkg / "fix.py").write_text(
        "def dead(x):\n"
        "    return x  # flowcheck: ignore[actor.swallow]\n"
    )
    bl = tmp_path / "baseline.json"
    man = tmp_path / "manifest.json"
    result = run_analysis(root=tmp_path, baseline_path=bl, manifest_path=man)
    assert [f.rule for f in result.new] == ["flowcheck.stale-ignore"]
    # freeze the baseline the way --write-baseline does...
    baseline_mod.save_baseline(result.findings, bl)
    # ...and the dead ignore STILL fails the gate
    again = run_analysis(root=tmp_path, baseline_path=bl, manifest_path=man)
    assert [f.rule for f in again.new] == ["flowcheck.stale-ignore"]
    assert not again.stale  # and it left no phantom baseline entry


# -- wire family (protocol contract; analysis/wire_registry.py) -------------


def _wire_rules(ctxs, tmp_path):
    from foundationdb_tpu.analysis.rules_wire import check_wire

    man = tmp_path / "wire.json"
    return [f.rule for f in check_wire(ctxs, manifest_path=man)]


def test_wire_frame_id_collision_flagged(tmp_path):
    ctxs = ctxs_from(
        'A = _message(0x0901, "A", [("v", "i64")])\n'
        'B = _message(0x0901, "B", [("v", "i64")])\n'
    )
    got = _wire_rules(ctxs, tmp_path)
    assert "wire.token-collision" in got
    # fix shape: distinct ids
    ctxs2 = ctxs_from(
        'A = _message(0x0901, "A", [("v", "i64")])\n'
        'B = _message(0x0902, "B", [("v", "i64")])\n'
    )
    assert "wire.token-collision" not in _wire_rules(ctxs2, tmp_path)


def test_wire_token_collision_flagged_but_not_across_namespaces(tmp_path):
    ctxs = ctxs_from(
        "TOKEN_A = 0x0111\nTOKEN_B = 0x0111\n"
    )
    assert "wire.token-collision" in _wire_rules(ctxs, tmp_path)
    # frame ids and endpoint tokens are DIFFERENT namespaces: the live
    # tree's TOKEN_RESOLVE (0x0101) numerically equals the
    # CommitTransaction frame id, and that is fine
    ctxs2 = ctxs_from(
        "TOKEN_A = 0x0901\n"
        'A = _message(0x0901, "A", [("v", "i64")])\n'
    )
    assert "wire.token-collision" not in _wire_rules(ctxs2, tmp_path)


_WIRE_PAIR = """\
def w_thing(out, t):
    w_u32(out, t.a)
    w_i64(out, t.b)


def r_thing(buf, off):
    a, off = r_u32(buf, off)
{dec_b}    return (Thing({kwargs}), off)


register(0x0901, Thing, w_thing, r_thing)
"""


def test_wire_codec_field_drift_flagged_and_paired_clean(tmp_path):
    # decoder skips the i64 the encoder wrote: op streams diverge
    short = _WIRE_PAIR.format(dec_b="", kwargs="a=a")
    assert "wire.codec-field-drift" in _wire_rules(
        ctxs_from(short), tmp_path
    )
    # decoder reads it but drops the field on the floor: field-set drift
    dropped = _WIRE_PAIR.format(
        dec_b="    b, off = r_i64(buf, off)\n", kwargs="a=a"
    )
    assert "wire.codec-field-drift" in _wire_rules(
        ctxs_from(dropped), tmp_path
    )
    # the fix shape: read AND reconstruct every encoded field
    paired = _WIRE_PAIR.format(
        dec_b="    b, off = r_i64(buf, off)\n", kwargs="a=a, b=b"
    )
    assert "wire.codec-field-drift" not in _wire_rules(
        ctxs_from(paired), tmp_path
    )


_WIRE_HANDLER = """\
TOKEN_PUSH = 0x0911
Push = _message(0x0910, "Push", [("version", "i64"), ("epoch", "i64")])


class Role:
    async def push(self, req: Push):
{body}

def setup(server, role):
    server.register(TOKEN_PUSH, role.push)
"""


def test_wire_epoch_unfenced_handler_fixture(tmp_path):
    tripped = _WIRE_HANDLER.format(
        body=(
            "        self.version = req.version\n"
            "        _fence_epoch(req, self)\n"
        )
    )
    assert "wire.epoch-unfenced-handler" in _wire_rules(
        ctxs_from(tripped), tmp_path
    )
    # the fix shape is exactly the silencing edit: fence first
    fenced = _WIRE_HANDLER.format(
        body=(
            "        _fence_epoch(req, self)\n"
            "        self.version = req.version\n"
        )
    )
    assert "wire.epoch-unfenced-handler" not in _wire_rules(
        ctxs_from(fenced), tmp_path
    )
    # the inline if-raise fence idiom (TLogRole.lock) also counts
    if_fenced = _WIRE_HANDLER.format(
        body=(
            "        if req.epoch < self.epoch:\n"
            "            raise RemoteError('stale')\n"
            "        self.version = req.version\n"
        )
    )
    assert "wire.epoch-unfenced-handler" not in _wire_rules(
        ctxs_from(if_fenced), tmp_path
    )


def test_wire_epoch_revert_acceptance_pin(tmp_path):
    """THE acceptance pin: surgically reverting ResolverRole's
    stale_epoch fence in the REAL multiprocess.py must trip
    wire.epoch-unfenced-handler; the shipped source must not."""
    mp_path = REPO / "foundationdb_tpu" / "cluster" / "multiprocess.py"
    codec_path = REPO / "foundationdb_tpu" / "wire" / "codec.py"
    src = mp_path.read_text(encoding="utf-8")
    fence = "        _fence_epoch(req, self)\n"
    assert fence in src
    reverted = src.replace(fence, "", 1)

    def run(mp_src):
        from foundationdb_tpu.analysis.rules_wire import check_wire

        ctxs = [
            FileContext(
                "foundationdb_tpu/cluster/multiprocess.py", mp_src
            ),
            FileContext(
                "foundationdb_tpu/wire/codec.py",
                codec_path.read_text(encoding="utf-8"),
            ),
        ]
        return [
            f for f in check_wire(ctxs, manifest_path=tmp_path / "w.json")
            if f.rule == "wire.epoch-unfenced-handler"
        ]

    assert run(src) == []
    tripped = run(reverted)
    assert tripped, "reverting the resolver fence must trip the rule"
    assert "ResolverRole.resolve" in tripped[0].message


def test_wire_call_timeout_and_classification(tmp_path):
    bare = (
        "async def f(conn, msg):\n"
        "    return await conn.call(TOKEN_PING, msg)\n"
    )
    got = _wire_rules(ctxs_from(bare), tmp_path)
    assert "wire.call-without-timeout" in got
    assert "wire.unclassified-error" in got
    # the fix shape: bounded call inside a classifying except
    fixed = (
        "async def f(conn, msg):\n"
        "    try:\n"
        "        return await conn.call(TOKEN_PING, msg, timeout=5.0)\n"
        "    except transport.TransportError as e:\n"
        "        raise transport.RemoteError(f'ping: {e!r}')\n"
    )
    got2 = _wire_rules(ctxs_from(fixed), tmp_path)
    assert "wire.call-without-timeout" not in got2
    assert "wire.unclassified-error" not in got2


def test_wire_manifest_drift_and_version_bump_message(tmp_path):
    from foundationdb_tpu.analysis.manifest import save_wire_manifest
    from foundationdb_tpu.analysis.rules_wire import (
        check_wire,
        tree_wire_manifest,
    )

    man = tmp_path / "wire.json"
    base = (
        "PROTOCOL_VERSION = 0x0001\n"
        'A = _message(0x0901, "A", [("v", "i64")])\n'
    )
    ctxs = ctxs_from(base)
    # no manifest yet: plain drift pointing at the writer workflow
    drift = [
        f for f in check_wire(ctxs, manifest_path=man)
        if f.rule == "wire.manifest-drift"
    ]
    assert drift and "--write-wire-manifest" in drift[0].message
    # write it: clean
    save_wire_manifest(tree_wire_manifest(ctxs), man)
    assert "wire.manifest-drift" not in [
        f.rule for f in check_wire(ctxs, manifest_path=man)
    ]
    # grow the message set WITHOUT bumping PROTOCOL_VERSION: the drift
    # finding must demand the bump
    ctxs2 = ctxs_from(
        base + 'B = _message(0x0902, "B", [("v", "i64")])\n'
    )
    drift2 = [
        f for f in check_wire(ctxs2, manifest_path=man)
        if f.rule == "wire.manifest-drift"
    ]
    assert drift2 and "PROTOCOL_VERSION bump" in drift2[0].message


def test_wire_ignore_comment_suppresses(tmp_path):
    src = (
        "async def f(conn, msg):\n"
        "    return await conn.call(  # flowcheck: ignore[wire.unclassified-error]\n"
        "        TOKEN_PING, msg, timeout=5.0\n"
        "    )\n"
    )
    got = _wire_rules(ctxs_from(src), tmp_path)
    assert "wire.unclassified-error" not in got


def test_wire_registry_matches_runtime_tables():
    """The extraction the gate and the fuzzer share agrees with the
    IMPORTED modules: every TOKEN_* constant and every registered
    frame id."""
    from foundationdb_tpu.analysis import wire_registry as wr
    from foundationdb_tpu.cluster import multiprocess as mp
    from foundationdb_tpu.wire import codec

    reg = wr.load_repo_registry(REPO)
    static_tokens = {t.name: t.value for t in reg.tokens}
    runtime_tokens = {
        name: getattr(mp, name)
        for name in dir(mp) if name.startswith("TOKEN_")
    }
    assert static_tokens == runtime_tokens
    assert {f.type_id for f in reg.frames} == set(codec._REGISTRY)
    # the fencing contract covers exactly the epoch-carrying frames
    # (TLogLockReply carries the epoch BACK; replies have no handler,
    # so only the request frames feed the unfenced-handler rule)
    assert reg.epoch_frames() == {
        "TLogPush", "TLogPop", "TLogLock", "TLogLockReply",
        "ResolveTransactionBatchRequest", "ResolveBatchColumnar",
        # the sequencer's allotment RPCs are generation-fenced too: a
        # fenced-out proxy must not receive grants (r19 scale-out)
        "GetCommitVersionRequest", "ReportRawCommittedVersionRequest",
    }


def test_live_tree_wire_manifest_is_current():
    from foundationdb_tpu.analysis.manifest import load_wire_manifest
    from foundationdb_tpu.analysis.rules_wire import tree_wire_manifest

    result = run_analysis(root=REPO)
    assert tree_wire_manifest(result.contexts) == load_wire_manifest(), (
        "wire_manifest.json is stale: run `python -m "
        "foundationdb_tpu.analysis --write-wire-manifest`"
    )


# -- res family: resource-ownership leaks ----------------------------------


def test_res_leak_on_unprotected_await_and_protected_clean():
    """The wire cluster's four hand-caught review fixes, as a rule: a
    live connection across an unprotected await leaks on the exception
    edge; an except-BaseException cleanup (ProxyRole.start's fixed
    shape) protects it."""
    leaky = (
        "from foundationdb_tpu.wire import transport\n\n"
        "class ProxyRole:\n"
        "    async def start(self, addr, msg):\n"
        "        conn = transport.RpcConnection(addr)\n"
        "        await conn.connect()\n"
        "        reply = await conn.call(1, msg)\n"
        "        self._conn = conn\n"
    )
    assert rules_of(analyze_source(leaky, SIM)) == [
        "res.leak-on-error-path"
    ]
    fixed = (
        "from foundationdb_tpu.wire import transport\n\n"
        "class ProxyRole:\n"
        "    async def start(self, addr, msg):\n"
        "        conn = transport.RpcConnection(addr)\n"
        "        await conn.connect()\n"
        "        try:\n"
        "            reply = await conn.call(1, msg)\n"
        "        except BaseException:\n"
        "            await conn.close()\n"
        "            raise\n"
        "        self._conn = conn\n"
    )
    assert analyze_source(fixed, SIM) == []


def test_res_bare_activation_is_not_a_finding():
    """`conn = RpcConnection(...); await conn.connect()` with no try is
    clean: an exception AT the activation escapes the PRE state (the
    transport cleans up its own half-open socket)."""
    src = (
        "from foundationdb_tpu.wire import transport\n\n"
        "async def dial(addr):\n"
        "    conn = transport.RpcConnection(addr)\n"
        "    await conn.connect()\n"
        "    return conn\n"
    )
    assert analyze_source(src, SIM) == []


def test_res_server_leak_and_try_finally_clean():
    """_serve_role's fixed shape: a started RpcServer awaited-on
    forever must close in a finally; without it the cancellation edge
    leaks the listener."""
    leaky = (
        "import asyncio\n\n"
        "from foundationdb_tpu.wire import transport\n\n"
        "async def serve(addr):\n"
        "    server = transport.RpcServer(addr)\n"
        "    await server.start()\n"
        "    await asyncio.Event().wait()\n"
    )
    # OUT scope: wire/ is the asyncio side (determinism.asyncio would
    # also fire in sim scope, correctly — different family's business)
    assert rules_of(analyze_source(leaky, OUT)) == [
        "res.leak-on-error-path"
    ]
    fixed = (
        "import asyncio\n\n"
        "from foundationdb_tpu.wire import transport\n\n"
        "async def serve(addr):\n"
        "    server = transport.RpcServer(addr)\n"
        "    await server.start()\n"
        "    try:\n"
        "        await asyncio.Event().wait()\n"
        "    finally:\n"
        "        await server.close()\n"
    )
    assert analyze_source(fixed, OUT) == []


def test_res_task_stored_on_self_needs_reachable_release():
    """WorkerRole's fixed shape: a task stored on self must be
    cancellable from some method — and the null-then-release ALIAS
    idiom (`task = self._t; self._t = None; task.cancel()`) counts."""
    leaky = (
        "import asyncio\n\n"
        "class WorkerRole:\n"
        "    async def start(self):\n"
        "        self._reg_task = asyncio.ensure_future(self._loop())\n"
    )
    assert rules_of(analyze_source(leaky, OUT)) == ["res.task-unowned"]
    fixed = leaky + (
        "\n"
        "    async def stop(self):\n"
        "        task = self._reg_task\n"
        "        self._reg_task = None\n"
        "        if task is not None:\n"
        "            task.cancel()\n"
    )
    assert analyze_source(fixed, OUT) == []


def test_res_task_discard_and_unowned_local():
    discard = (
        "import asyncio\n\n"
        "async def f(coro):\n"
        "    asyncio.create_task(coro)\n"
    )
    assert rules_of(analyze_source(discard, OUT)) == ["res.task-unowned"]
    unowned = (
        "import asyncio\n\n"
        "async def f(coro):\n"
        "    t = asyncio.create_task(coro)\n"
    )
    assert rules_of(analyze_source(unowned, OUT)) == ["res.task-unowned"]
    owned = (
        "import asyncio\n\n"
        "async def f(coro):\n"
        "    t = asyncio.create_task(coro)\n"
        "    await t\n"
    )
    assert analyze_source(owned, OUT) == []


def test_res_double_close_and_use_after_close():
    double = (
        "from foundationdb_tpu.wire import transport\n\n"
        "async def f(addr):\n"
        "    conn = transport.RpcConnection(addr)\n"
        "    await conn.connect()\n"
        "    await conn.close()\n"
        "    await conn.close()\n"
    )
    assert rules_of(analyze_source(double, SIM)) == ["res.double-close"]
    use = (
        "from foundationdb_tpu.wire import transport\n\n"
        "async def f(addr, msg):\n"
        "    conn = transport.RpcConnection(addr)\n"
        "    await conn.connect()\n"
        "    await conn.close()\n"
        "    return await conn.call(1, msg)\n"
    )
    assert rules_of(analyze_source(use, SIM)) == ["res.transfer-then-use"]
    # close-then-reacquire-then-close is NOT a double close
    ok = (
        "from foundationdb_tpu.wire import transport\n\n"
        "async def f(addr):\n"
        "    conn = transport.RpcConnection(addr)\n"
        "    await conn.connect()\n"
        "    await conn.close()\n"
        "    conn = transport.RpcConnection(addr)\n"
        "    await conn.connect()\n"
        "    await conn.close()\n"
    )
    assert analyze_source(ok, SIM) == []


def test_res_none_narrowing_kills_infeasible_paths():
    """`if conn is not None: await conn.close()` after a tracked
    acquire must NOT leak through the infeasible None branch — but a
    close behind an UNRELATED condition still can."""
    src = (
        "from foundationdb_tpu.wire import transport\n\n"
        "async def f(addr):\n"
        "    conn = transport.RpcConnection(addr)\n"
        "    await conn.connect()\n"
        "    if conn is not None:\n"
        "        await conn.close()\n"
    )
    assert analyze_source(src, SIM) == []
    leaky = (
        "from foundationdb_tpu.wire import transport\n\n"
        "async def f(addr, flag):\n"
        "    conn = transport.RpcConnection(addr)\n"
        "    await conn.connect()\n"
        "    if flag:\n"
        "        await conn.close()\n"
    )
    assert rules_of(analyze_source(leaky, SIM)) == [
        "res.leak-on-error-path"
    ]


def test_res_helper_acquire_is_interprocedural():
    """A module helper that returns its acquire (mp.connect's shape)
    makes the CALLER the owner: discarding its result is a leak."""
    src = (
        "from foundationdb_tpu.wire import transport\n\n"
        "async def connect(addr):\n"
        "    conn = transport.RpcConnection(addr)\n"
        "    await conn.connect()\n"
        "    return conn\n\n"
        "async def f(addr, msg):\n"
        "    c = await connect(addr)\n"
        "    await c.call(1, msg)\n"
        "    await c.close()\n"
    )
    assert rules_of(analyze_source(src, SIM)) == ["res.leak-on-error-path"]
    fixed = (
        "from foundationdb_tpu.wire import transport\n\n"
        "async def connect(addr):\n"
        "    conn = transport.RpcConnection(addr)\n"
        "    await conn.connect()\n"
        "    return conn\n\n"
        "async def f(addr, msg):\n"
        "    c = await connect(addr)\n"
        "    try:\n"
        "        await c.call(1, msg)\n"
        "    finally:\n"
        "        await c.close()\n"
    )
    assert analyze_source(fixed, SIM) == []


def test_res_revert_acceptance_pin():
    """THE res acceptance pin: surgically reverting ClusterClient.
    _refresh's failed-probe connection close (a PR-13-era leak fix) in
    the REAL multiprocess.py must trip res.leak-on-error-path naming
    _refresh; the shipped source must analyze clean."""
    mp_path = REPO / "foundationdb_tpu" / "cluster" / "multiprocess.py"
    src = mp_path.read_text(encoding="utf-8")
    close_fix = (
        "                        if conn is not None:\n"
        "                            try:\n"
        "                                await conn.close()\n"
        "                            except Exception:\n"
        "                                pass\n"
    )
    assert close_fix in src, "the _refresh failed-probe close moved"
    reverted = src.replace(close_fix, "", 1)

    rel = "foundationdb_tpu/cluster/multiprocess.py"
    assert [
        f for f in analyze_source(src, rel)
        if f.rule.startswith("res.")
    ] == []
    tripped = [
        f for f in analyze_source(reverted, rel)
        if f.rule == "res.leak-on-error-path"
    ]
    assert tripped, "reverting the probe-close must trip the leak rule"
    assert any("_refresh" in f.message for f in tripped)


def test_res_family_in_catalog():
    from foundationdb_tpu.analysis.registry import RULES, load_rules

    load_rules()
    for rid in ("res.leak-on-error-path", "res.task-unowned",
                "res.double-close", "res.transfer-then-use"):
        assert rid in RULES and RULES[rid].doc
