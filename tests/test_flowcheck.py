"""flowcheck: every rule family exercised on fixtures, plus the live
tree self-check (zero non-baselined violations — the CI gate contract).

Fixture snippets are linted through `analyze_source`, which runs the
file-level rules as if the snippet lived at a chosen path — the path is
what selects scope (sim-schedulable vs kernel vs out-of-scope), so the
same snippet can assert both the positive and the scope-negative case.
"""

from pathlib import Path

import pytest

from foundationdb_tpu.analysis import analyze_source, run_analysis
from foundationdb_tpu.analysis.manifest import load_manifest
from foundationdb_tpu.analysis.rules_probes import (
    check_probe_ledger,
    tree_manifest,
)
from foundationdb_tpu.analysis.walker import FileContext

SIM = "foundationdb_tpu/cluster/_snippet.py"
OPS = "foundationdb_tpu/ops/_snippet.py"
OUT = "foundationdb_tpu/wire/_snippet.py"  # outside every scope

REPO = Path(__file__).resolve().parents[1]


def rules_of(findings):
    return [f.rule for f in findings]


# -- determinism family ----------------------------------------------------


def test_wall_clock_flagged_in_sim_scope():
    src = "import time\n\ndef f():\n    return time.time()\n"
    assert rules_of(analyze_source(src, SIM)) == ["determinism.wall-clock"]
    # aliased import still resolves
    src2 = "import time as _t\n\ndef f():\n    _t.sleep(1)\n"
    assert rules_of(analyze_source(src2, SIM)) == ["determinism.wall-clock"]
    # from-import too
    src3 = "from time import monotonic\n\ndef f():\n    return monotonic()\n"
    assert rules_of(analyze_source(src3, SIM)) == ["determinism.wall-clock"]


def test_wall_clock_out_of_scope_and_negative():
    src = "import time\n\ndef f():\n    return time.time()\n"
    assert analyze_source(src, OUT) == []  # wire/ is the real-I/O side
    ok = "def f(sched):\n    return sched.now()\n"
    assert analyze_source(ok, SIM) == []


def test_datetime_now_flagged():
    src = (
        "import datetime\n\ndef f():\n"
        "    return datetime.datetime.now()\n"
    )
    assert rules_of(analyze_source(src, SIM)) == ["determinism.wall-clock"]
    # dot-boundary: a sim-clock wrapper merely NAMED *datetime is fine
    ok = "def f(start_datetime):\n    return start_datetime.now()\n"
    assert analyze_source(ok, SIM) == []


def test_unseeded_random_flagged():
    src = (
        "import os, random\nimport numpy as np\n\ndef f():\n"
        "    a = os.urandom(8)\n"
        "    b = random.random()\n"
        "    c = np.random.rand(3)\n"
        "    d = np.random.default_rng(0)\n"  # seeded: NOT flagged
        "    return a, b, c, d\n"
    )
    assert rules_of(analyze_source(src, SIM)) == [
        "determinism.unseeded-random"
    ] * 3


def test_asyncio_flagged_in_sim_scope():
    src = "import asyncio\n\nasync def f():\n    await asyncio.sleep(1)\n"
    got = rules_of(analyze_source(src, SIM))
    assert got == ["determinism.asyncio"] * 2  # import + call
    assert analyze_source(src, OUT) == []


def test_suppression_comment_absorbs_the_finding():
    src = (
        "import time\n\ndef f():\n"
        "    return time.time()  # flowcheck: ignore[determinism.wall-clock]\n"
    )
    assert analyze_source(src, SIM) == []
    # family-level and bare ignores work too
    fam = (
        "import time\n\ndef f():\n"
        "    return time.time()  # flowcheck: ignore[determinism]\n"
    )
    assert analyze_source(fam, SIM) == []
    bare = (
        "import time\n\ndef f():\n"
        "    return time.time()  # flowcheck: ignore\n"
    )
    assert analyze_source(bare, SIM) == []
    # a suppression for a DIFFERENT rule does not absorb it
    wrong = (
        "import time\n\ndef f():\n"
        "    return time.time()  # flowcheck: ignore[actor.swallow]\n"
    )
    assert rules_of(analyze_source(wrong, SIM)) == ["determinism.wall-clock"]


def test_trailing_suppression_does_not_bleed_to_next_line():
    """A justified trailing ignore on line N must not absorb an
    unrelated violation on line N+1; a STANDALONE comment line
    annotates the line below it."""
    src = (
        "import time\n\ndef f():\n"
        "    a = time.time()  # flowcheck: ignore[determinism]\n"
        "    time.sleep(1)\n"
        "    return a\n"
    )
    got = analyze_source(src, SIM)
    assert rules_of(got) == ["determinism.wall-clock"]
    assert got[0].line == 5  # the sleep, not the suppressed time()
    above = (
        "import time\n\ndef f():\n"
        "    # flowcheck: ignore[determinism]\n"
        "    return time.time()\n"
    )
    assert analyze_source(above, SIM) == []


def test_tuple_and_attribute_broad_excepts_flagged():
    """`except (Exception, ValueError): pass` and
    `except builtins.Exception: pass` must not evade actor.swallow."""
    tup = (
        "def f(x):\n    try:\n        x()\n"
        "    except (Exception, ValueError):\n        pass\n"
    )
    assert rules_of(analyze_source(tup, SIM)) == ["actor.swallow"]
    attr = (
        "import builtins\n\ndef f(x):\n    try:\n        x()\n"
        "    except builtins.Exception:\n        pass\n"
    )
    assert rules_of(analyze_source(attr, SIM)) == ["actor.swallow"]
    # a narrow tuple stays fine
    ok = (
        "def f(x):\n    try:\n        x()\n"
        "    except (KeyError, ValueError):\n        pass\n"
    )
    assert analyze_source(ok, SIM) == []


def test_suppression_inside_string_literal_is_inert():
    """Only REAL comments suppress: a string (or docstring) merely
    mentioning the marker syntax must not blind the gate."""
    src = (
        "import time\n\ndef f():\n"
        "    msg = 'add # flowcheck: ignore to silence'\n"
        "    return time.time(), msg\n"
    )
    assert rules_of(analyze_source(src, SIM)) == ["determinism.wall-clock"]
    # marker in a string ON the offending line: still inert
    same_line = (
        "import time\n\ndef f():\n"
        "    return time.time(), '# flowcheck: ignore'\n"
    )
    assert rules_of(analyze_source(same_line, SIM)) == [
        "determinism.wall-clock"
    ]


# -- actor-safety family ---------------------------------------------------


def test_fire_and_forget_spawn_flagged():
    src = "def f(sched, coro):\n    sched.spawn(coro)\n"
    assert rules_of(analyze_source(src, SIM)) == ["actor.fire-and-forget"]
    ok = "def f(sched, coro):\n    t = sched.spawn(coro)\n    return t\n"
    assert analyze_source(ok, SIM) == []
    sup = (
        "def f(sched, coro):\n"
        "    sched.spawn(coro)  # flowcheck: ignore[actor.fire-and-forget]\n"
    )
    assert analyze_source(sup, SIM) == []


def test_unawaited_future_flagged():
    src = "async def f(sched):\n    sched.delay(1.0)\n"
    assert rules_of(analyze_source(src, SIM)) == ["actor.unawaited-future"]
    ok = "async def f(sched):\n    await sched.delay(1.0)\n"
    assert analyze_source(ok, SIM) == []


def test_bare_local_coroutine_call_flagged():
    src = (
        "async def worker():\n    pass\n\n"
        "def f():\n    worker()\n"
    )
    assert rules_of(analyze_source(src, SIM)) == ["actor.unawaited-future"]


def test_broad_swallow_flagged():
    src = (
        "def f(x):\n    try:\n        x()\n"
        "    except Exception:\n        pass\n"
    )
    assert rules_of(analyze_source(src, SIM)) == ["actor.swallow"]
    bare = (
        "def f(x):\n    try:\n        x()\n"
        "    except:\n        pass\n"
    )
    assert rules_of(analyze_source(bare, SIM)) == ["actor.swallow"]
    # narrow type or a body that DOES something: fine
    ok = (
        "def f(x, log):\n    try:\n        x()\n"
        "    except KeyError:\n        pass\n"
        "    try:\n        x()\n"
        "    except Exception as e:\n        log(e)\n"
    )
    assert analyze_source(ok, SIM) == []


# -- JAX hazard family -----------------------------------------------------


def test_host_sync_flagged_in_kernel_scope():
    src = "def f(x):\n    return float(x)\n"
    assert rules_of(analyze_source(src, OPS)) == ["jax.host-sync"]
    assert analyze_source(src, SIM) == []  # kernel scope only
    ok = "def f():\n    return float(1.5)\n"  # literal: static
    assert analyze_source(ok, OPS) == []
    item = "def f(x):\n    return x.item()\n"
    assert rules_of(analyze_source(item, OPS)) == ["jax.host-sync"]


def test_host_numpy_flagged_in_kernel_scope():
    src = (
        "import numpy as np\n\ndef f(a, b):\n"
        "    return np.maximum(a, b)\n"
    )
    assert rules_of(analyze_source(src, OPS)) == ["jax.host-numpy"]
    # exactly ONE finding per call: np.nonzero is host-numpy, not also
    # double-reported as data-dep-shape
    dd = (
        "import numpy as np\n\ndef f(x):\n"
        "    return np.nonzero(x)\n"
    )
    assert rules_of(analyze_source(dd, OPS)) == ["jax.host-numpy"]
    ok = (
        "import jax.numpy as jnp\n\ndef f(a, b):\n"
        "    return jnp.maximum(a, b)\n"
    )
    assert analyze_source(ok, OPS) == []


def test_data_dependent_shape_flagged():
    src = (
        "import jax.numpy as jnp\n\ndef f(x):\n"
        "    return jnp.nonzero(x)\n"
    )
    assert rules_of(analyze_source(src, OPS)) == ["jax.data-dep-shape"]
    one_arg = (
        "import jax.numpy as jnp\n\ndef f(x):\n"
        "    return jnp.where(x)\n"
    )
    assert rules_of(analyze_source(one_arg, OPS)) == ["jax.data-dep-shape"]
    ok = (
        "import jax.numpy as jnp\n\ndef f(c, a, b):\n"
        "    return jnp.where(c, a, b)\n"
    )
    assert analyze_source(ok, OPS) == []


def test_block_until_ready_in_loop_flagged_everywhere():
    src = (
        "def f(outs):\n    for o in outs:\n"
        "        o.block_until_ready()\n"
    )
    # package-wide rule: fires even outside kernel scope
    assert rules_of(analyze_source(src, OUT)) == ["jax.block-in-loop"]
    ok = (
        "def f(outs):\n    outs[-1].block_until_ready()\n"
    )
    assert analyze_source(ok, OUT) == []


# -- probe accounting family (tree checks) ---------------------------------


def ctxs_from(*sources):
    return [
        FileContext(f"foundationdb_tpu/cluster/_fix{i}.py", src)
        for i, src in enumerate(sources)
    ]


def test_undeclared_probe_flagged(tmp_path):
    man = tmp_path / "m.json"
    ctxs = ctxs_from(
        "from foundationdb_tpu.utils.probes import code_probe\n"
        "def f():\n    code_probe(True, 'x.y')\n"
    )
    got = [f.rule for f in check_probe_ledger(ctxs, manifest_path=man)]
    assert "probe.undeclared" in got


def test_duplicate_declare_flagged(tmp_path):
    man = tmp_path / "m.json"
    ctxs = ctxs_from(
        "from foundationdb_tpu.utils.probes import declare\n"
        "declare('dup.probe')\n",
        "from foundationdb_tpu.utils.probes import declare\n"
        "declare('dup.probe')\n",
    )
    got = [f.rule for f in check_probe_ledger(ctxs, manifest_path=man)]
    assert "probe.duplicate" in got


def test_dynamic_probe_name_flagged(tmp_path):
    man = tmp_path / "m.json"
    ctxs = ctxs_from(
        "from foundationdb_tpu.utils.probes import code_probe\n"
        "def f(name):\n    code_probe(True, name)\n"
    )
    got = [f.rule for f in check_probe_ledger(ctxs, manifest_path=man)]
    assert "probe.dynamic-name" in got


def test_keyword_probe_name_is_accounted(tmp_path):
    """code_probe(cond, name='x.y') must not slip past the ledger."""
    man = tmp_path / "m.json"
    ctxs = ctxs_from(
        "from foundationdb_tpu.utils.probes import code_probe\n"
        "def f():\n    code_probe(True, name='kw.probe')\n"
    )
    got = [f.rule for f in check_probe_ledger(ctxs, manifest_path=man)]
    assert "probe.undeclared" in got
    # non-literal keyword name is dynamic, not invisible
    ctxs2 = ctxs_from(
        "from foundationdb_tpu.utils.probes import code_probe\n"
        "def f(n):\n    code_probe(True, name=n)\n"
    )
    got2 = [f.rule for f in check_probe_ledger(ctxs2, manifest_path=man)]
    assert "probe.dynamic-name" in got2


def test_manifest_drift_flagged(tmp_path):
    man = tmp_path / "m.json"  # missing file = empty manifest
    ctxs = ctxs_from(
        "from foundationdb_tpu.utils.probes import declare, code_probe\n"
        "declare('a.b')\n"
        "def f():\n    code_probe(True, 'a.b')\n"
    )
    got = [f.rule for f in check_probe_ledger(ctxs, manifest_path=man)]
    assert got == ["probe.manifest-drift"]


# -- the live tree: the actual gate ----------------------------------------


def test_live_tree_has_zero_new_violations():
    """`python -m foundationdb_tpu.analysis` exit-0 equivalent: the
    tree, checked against the shipped baseline, is clean — and the
    baseline itself has no stale (already-fixed) entries."""
    result = run_analysis(root=REPO)
    assert result.ok, "NEW flowcheck violations:\n" + "\n".join(
        f.render() for f in result.new
    )
    assert not result.stale, (
        "baseline entries no longer match any finding (fixed code? "
        f"run --write-baseline): {dict(result.stale)}"
    )


def test_live_tree_manifest_is_current():
    result = run_analysis(root=REPO)
    assert tree_manifest(result.contexts) == load_manifest(), (
        "probe_manifest.json is stale: run `python -m "
        "foundationdb_tpu.analysis --write-manifest`"
    )


def test_rule_catalog_is_populated():
    from foundationdb_tpu.analysis import registry

    registry.load_rules()
    families = {r.family for r in registry.RULES.values()}
    assert {"determinism", "actor", "jax", "probe"} <= families
    assert len(registry.RULES) >= 13


def test_cli_entrypoint_exits_zero():
    """The exact command scripts/check.sh and CI run."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "foundationdb_tpu.analysis"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 new" in proc.stdout
