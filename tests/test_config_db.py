"""Dynamic knob broadcast tests."""

from foundationdb_tpu.cluster.config_db import (
    LocalConfiguration,
    clear_knob,
    read_overrides,
    set_knob,
)
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.utils.knobs import Knobs


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


def make_knobs():
    k = Knobs("test")
    k.define("COMMIT_BATCH_INTERVAL", 0.005)
    k.define("MAX_THING", 100)
    return k


def test_set_and_broadcast():
    sched, cluster, db = open_cluster(ClusterConfig())
    knobs = make_knobs()
    lc = LocalConfiguration(db, knobs)
    lc.start()

    async def body():
        await sched.delay(0.05)  # initial refresh
        assert knobs.MAX_THING == 100
        await set_knob(db, "MAX_THING", 250)
        await set_knob(db, "COMMIT_BATCH_INTERVAL", 0.02)
        await sched.delay(0.1)  # watch fires, overrides apply
        v1 = (knobs.MAX_THING, knobs.COMMIT_BATCH_INTERVAL)
        assert await read_overrides(db) == {
            "MAX_THING": 250, "COMMIT_BATCH_INTERVAL": 0.02
        }
        await clear_knob(db, "MAX_THING")
        await sched.delay(0.1)
        v2 = knobs.MAX_THING
        return v1, v2

    (v1, v2) = run(sched, body())
    assert v1 == (250, 0.02)
    assert v2 == 100  # cleared override reverts to the default
    lc.stop()
    cluster.stop()


def test_unknown_knob_ignored():
    sched, cluster, db = open_cluster(ClusterConfig())
    knobs = make_knobs()
    lc = LocalConfiguration(db, knobs)
    lc.start()

    async def body():
        await set_knob(db, "NO_SUCH_KNOB", 1)
        await set_knob(db, "MAX_THING", 7)
        await sched.delay(0.1)
        return knobs.MAX_THING

    assert run(sched, body()) == 7
    lc.stop()
    cluster.stop()
