"""Dynamic knob broadcast tests."""

from foundationdb_tpu.cluster.config_db import (
    LocalConfiguration,
    clear_knob,
    read_overrides,
    set_knob,
)
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.utils.knobs import Knobs


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


def make_knobs():
    k = Knobs("test")
    k.define("COMMIT_BATCH_INTERVAL", 0.005)
    k.define("MAX_THING", 100)
    return k


def test_set_and_broadcast():
    sched, cluster, db = open_cluster(ClusterConfig())
    knobs = make_knobs()
    lc = LocalConfiguration(db, knobs)
    lc.start()

    async def body():
        await sched.delay(0.05)  # initial refresh
        assert knobs.MAX_THING == 100
        await set_knob(db, "MAX_THING", 250)
        await set_knob(db, "COMMIT_BATCH_INTERVAL", 0.02)
        await sched.delay(0.1)  # watch fires, overrides apply
        v1 = (knobs.MAX_THING, knobs.COMMIT_BATCH_INTERVAL)
        assert await read_overrides(db) == {
            "MAX_THING": 250, "COMMIT_BATCH_INTERVAL": 0.02
        }
        await clear_knob(db, "MAX_THING")
        await sched.delay(0.1)
        v2 = knobs.MAX_THING
        return v1, v2

    (v1, v2) = run(sched, body())
    assert v1 == (250, 0.02)
    assert v2 == 100  # cleared override reverts to the default
    lc.stop()
    cluster.stop()


def test_unknown_knob_ignored():
    sched, cluster, db = open_cluster(ClusterConfig())
    knobs = make_knobs()
    lc = LocalConfiguration(db, knobs)
    lc.start()

    async def body():
        await set_knob(db, "NO_SUCH_KNOB", 1)
        await set_knob(db, "MAX_THING", 7)
        await sched.delay(0.1)
        return knobs.MAX_THING

    assert run(sched, body()) == 7
    lc.stop()
    cluster.stop()


def test_knob_survives_coordinator_minority():
    """VERDICT r4 task 7: the authoritative knob store is the coordinator
    quorum (PaxosConfigStore) — a minority outage neither blocks knob
    writes nor loses knob data, and a wiped data-plane copy is restored
    from the quorum (fdbserver/ConfigNode.actor.cpp discipline)."""
    from foundationdb_tpu.cluster.config_db import (
        CONF_PREFIX,
        PaxosConfigStore,
        restore_broadcast,
    )

    sched, cluster, db = open_cluster(ClusterConfig())
    knobs = make_knobs()
    lc = LocalConfiguration(db, knobs)
    lc.start()

    async def body():
        cluster.kill_coordinator(0)  # minority of the 3
        await set_knob(db, "MAX_THING", 42)  # quorum write still commits
        await sched.delay(0.1)
        assert knobs.MAX_THING == 42
        cluster.revive_coordinator(0)

        # an INDEPENDENT quorum client sees the committed override
        fresh = PaxosConfigStore(sched, cluster.config_nodes, "reader2")
        gen, overrides = await fresh.snapshot()
        assert overrides == {"MAX_THING": b"42"} and gen >= 1

        # wipe the broadcast copy (data-plane loss stand-in), restore
        txn = db.create_transaction()
        txn.clear_range(CONF_PREFIX, CONF_PREFIX + b"\xff")
        await txn.commit()
        assert await read_overrides(db) == {}
        restored = await restore_broadcast(db)
        assert restored == {"MAX_THING": 42}
        assert await read_overrides(db) == {"MAX_THING": 42}
        await sched.delay(0.1)
        assert knobs.MAX_THING == 42

    run(sched, body())
    lc.stop()
    cluster.stop()


def test_racing_knob_writers_converge():
    """Two independent quorum clients race read-modify-write rounds;
    StaleGeneration retries (config.quorum_write_raced) must leave BOTH
    overrides present — the PaxosConfigTransaction commit-loop contract."""
    from foundationdb_tpu.cluster.config_db import PaxosConfigStore

    sched, cluster, db = open_cluster(ClusterConfig())
    a = PaxosConfigStore(sched, cluster.config_nodes, "writer-a")
    b = PaxosConfigStore(sched, cluster.config_nodes, "writer-b")

    async def body():
        ta = sched.spawn(a.set("KNOB_A", b"1"))
        tb = sched.spawn(b.set("KNOB_B", b"2"))
        await ta.done
        await tb.done
        _gen, overrides = await a.snapshot()
        assert overrides == {"KNOB_A": b"1", "KNOB_B": b"2"}

    run(sched, body())
    cluster.stop()


def test_knob_write_retries_through_quorum_outage():
    """The round-5 crash shape, fixed: a coordinator MAJORITY dies
    mid-`set`; instead of QuorumUnreachable escaping the actor (264
    unhandled tracebacks across the r5 re-run soak), the store backs
    off with capped delays and the write lands once quorum returns —
    and the scheduler's unhandled-error ledger stays empty."""
    from foundationdb_tpu.cluster.config_db import PaxosConfigStore
    from foundationdb_tpu.utils import probes

    sched, cluster, db = open_cluster(ClusterConfig())
    store = PaxosConfigStore(sched, cluster.config_nodes, "outage-writer")

    async def body():
        # majority down BEFORE the write even reads: first snapshot
        # already sees QuorumUnreachable
        cluster.kill_coordinator(0)
        cluster.kill_coordinator(1)
        t = sched.spawn(store.set("MAX_THING", b"77"))
        await sched.delay(0.4)  # write is backing off meanwhile
        assert not t.done.is_ready  # genuinely blocked on the outage
        cluster.revive_coordinator(0)
        cluster.revive_coordinator(1)
        gen, overrides = await t.done  # succeeds after quorum returns
        assert overrides["MAX_THING"] == b"77"
        fresh = PaxosConfigStore(sched, cluster.config_nodes, "reader")
        _g, seen = await fresh.snapshot()
        assert seen["MAX_THING"] == b"77"

    run(sched, body())
    assert probes.snapshot().get("config.quorum_write_retried", 0) >= 1
    assert sched.unhandled_errors() == []
    cluster.stop()


def test_knob_write_fails_loudly_when_outage_outlives_budget():
    """A PERMANENT quorum loss must still fail loudly (the retry is for
    transient outages, not a license to hang forever)."""
    import pytest as _pytest

    from foundationdb_tpu.cluster.config_db import PaxosConfigStore
    from foundationdb_tpu.cluster.coordination import QuorumUnreachable

    sched, cluster, db = open_cluster(ClusterConfig())
    store = PaxosConfigStore(sched, cluster.config_nodes, "doomed-writer")

    async def body():
        cluster.kill_coordinator(0)
        cluster.kill_coordinator(1)
        cluster.kill_coordinator(2)
        with _pytest.raises(QuorumUnreachable):
            await store.set("MAX_THING", b"88")
        return True

    assert run(sched, body())
    cluster.stop()
