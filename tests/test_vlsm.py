"""VersionedLsm engine tests: versioned reads, durability, compaction,
GC floor, restart cost, and bounded memory with data >> memtable.

The engine is the StorageRole's persistent store (native/vlsm.cpp) —
the build's answer to the reference's on-disk engines
(fdbserver/VersionedBTree.actor.cpp Redwood / KeyValueStoreSQLite):
data > RAM via sorted runs + pread, restart ∝ WAL tail, at-version MVCC
reads with floor GC.
"""

import os

import pytest

from foundationdb_tpu.native import VersionedLsm

S = VersionedLsm.MUT_SET
C = VersionedLsm.MUT_CLEAR_RANGE


def test_versioned_point_reads(tmp_path):
    db = VersionedLsm(str(tmp_path / "db"))
    db.apply(10, [(S, b"a", b"v10")])
    db.apply(20, [(S, b"a", b"v20"), (S, b"b", b"bee")])
    assert db.get(b"a", 9) is None
    assert db.get(b"a", 10) == b"v10"
    assert db.get(b"a", 19) == b"v10"
    assert db.get(b"a", 20) == b"v20"
    assert db.get(b"b", 15) is None
    assert db.get(b"b", 25) == b"bee"
    # same answers after a flush (run-resident)
    db.flush()
    assert db.get(b"a", 19) == b"v10"
    assert db.get(b"a", 20) == b"v20"


def test_clear_range_versions(tmp_path):
    db = VersionedLsm(str(tmp_path / "db"))
    db.apply(10, [(S, b"k1", b"a"), (S, b"k2", b"b"), (S, b"k3", b"c")])
    db.apply(20, [(C, b"k1", b"k3")])  # clears k1, k2; k3 survives
    db.apply(30, [(S, b"k2", b"reborn")])
    for probe in (lambda: None, db.flush):
        probe()
        assert db.get(b"k1", 15) == b"a"
        assert db.get(b"k1", 25) is None
        assert db.get(b"k2", 25) is None
        assert db.get(b"k2", 30) == b"reborn"
        assert db.get(b"k3", 25) == b"c"


def test_within_version_order(tmp_path):
    """Mutation order inside one version is authoritative: set after
    clear survives, clear after set kills."""
    db = VersionedLsm(str(tmp_path / "db"))
    db.apply(5, [(S, b"x", b"old"), (S, b"y", b"old")])
    db.apply(10, [(C, b"a", b"z"), (S, b"x", b"new")])
    assert db.get(b"x", 10) == b"new"
    assert db.get(b"y", 10) is None
    db.flush()
    assert db.get(b"x", 10) == b"new"
    assert db.get(b"y", 10) is None


def test_within_version_set_then_clear(tmp_path):
    """The mirror case: a clear AFTER a set at the same version kills
    the key (the memory engine's apply-order semantics — code-review r4
    found the original tie-break inverted this)."""
    db = VersionedLsm(str(tmp_path / "db"))
    db.apply(10, [(S, b"k", b"val"), (C, b"a", b"z")])
    assert db.get(b"k", 10) is None
    db.flush()
    assert db.get(b"k", 10) is None
    # and after compaction with the floor above it, the key is gone
    db.set_floor(20)
    db.compact()
    assert db.get(b"k", 20) is None
    assert db.range(b"", b"", 20) == []


def test_key_versions_straddle_index_boundary(tmp_path):
    """Older versions of a key sitting at the tail of the previous
    sparse-index block must still be found (code-review r4: seek_block
    landed ON the equal index key and skipped them)."""
    db = VersionedLsm(str(tmp_path / "db"))
    muts = [(S, b"fill%04d" % i, b"x") for i in range(15)]
    db.apply(100, muts + [(S, b"kk", b"v0")])
    for i in range(1, 6):
        db.apply(100 + i, [(S, b"kk", b"v%d" % i)])
    db.flush()
    for i in range(6):
        assert db.get(b"kk", 100 + i) == b"v%d" % i, i


def test_restart_recovers_runs_not_memtable(tmp_path):
    d = str(tmp_path / "db")
    db = VersionedLsm(d)
    db.apply(10, [(S, b"durable", b"yes")])
    assert db.flush() == 10
    db.apply(20, [(S, b"volatile", b"lost")])  # never flushed
    db.close()

    db2 = VersionedLsm(d)
    assert db2.durable_version == 10
    assert db2.get(b"durable", 10) == b"yes"
    # the memtable died with the process — the caller's WAL replays it
    assert db2.get(b"volatile", 20) is None


def test_range_scan_merges_sources(tmp_path):
    db = VersionedLsm(str(tmp_path / "db"))
    db.apply(10, [(S, b"a", b"1"), (S, b"c", b"3")])
    db.flush()
    db.apply(20, [(S, b"b", b"2"), (C, b"c", b"d")])
    # at v=10: a, c; at v=20: a, b (c cleared)
    assert db.range(b"", b"\xff", 10) == [(b"a", b"1"), (b"c", b"3")]
    assert db.range(b"", b"\xff", 20) == [(b"a", b"1"), (b"b", b"2")]
    db.flush()
    assert db.range(b"a", b"c", 20) == [(b"a", b"1"), (b"b", b"2")]
    assert db.range(b"b", b"\xff", 10) == [(b"c", b"3")]


def test_floor_gc_compacts_but_keeps_window(tmp_path):
    db = VersionedLsm(str(tmp_path / "db"))
    for v in range(1, 11):
        db.apply(v, [(S, b"k", b"v%d" % v)])
        db.flush()
    db.set_floor(5)
    db.compact()
    assert db.num_runs == 1
    # at the floor: collapsed to the floor winner. (Below the floor is
    # out of contract — the role raises transaction_too_old there, the
    # reference's VersionedMap::forgetVersionsBefore discipline.)
    assert db.get(b"k", 5) == b"v5"
    # above the floor: exact
    for v in range(5, 11):
        assert db.get(b"k", v) == b"v%d" % v


def test_floor_gc_drops_cleared_keys(tmp_path):
    db = VersionedLsm(str(tmp_path / "db"))
    db.apply(1, [(S, b"dead", b"x"), (S, b"live", b"y")])
    db.apply(2, [(C, b"dead", b"dead\x00")])
    db.flush()
    db.set_floor(10)
    db.compact()
    assert db.get(b"dead", 10) is None
    assert db.get(b"live", 10) == b"y"
    # the dead key is physically gone, not just shadowed
    assert db.range(b"", b"\xff", 10) == [(b"live", b"y")]


def test_data_larger_than_memtable_budget(tmp_path):
    """Stream 20k keys through a tiny flush budget: memtable stays
    bounded, reads come off disk runs, compaction keeps the run count
    flat, and a reopen sees everything durable."""
    d = str(tmp_path / "db")
    db = VersionedLsm(d)
    budget = 64 * 1024
    n, version = 20_000, 0
    for i in range(0, n, 500):
        version += 1
        db.apply(version, [
            (S, b"key%08d" % j, b"val%08d" % j) for j in range(i, i + 500)
        ])
        if db.mem_bytes > budget:
            db.flush()
    db.flush()
    assert db.mem_bytes == 0
    assert db.num_runs <= 9  # compaction trigger keeps the tier flat
    for j in (0, 1, 499, 500, 12345, n - 1):
        assert db.get(b"key%08d" % j, version) == b"val%08d" % j
    db.close()

    db2 = VersionedLsm(d)
    assert db2.durable_version == version
    for j in (0, 777, n - 1):
        assert db2.get(b"key%08d" % j, version) == b"val%08d" % j
    assert len(db2.range(b"", b"\xff", version)) == n


def test_orphan_run_swept_on_open(tmp_path):
    d = str(tmp_path / "db")
    db = VersionedLsm(d)
    db.apply(1, [(S, b"a", b"1")])
    db.flush()
    db.close()
    # simulate a crash between run fsync and manifest rename
    orphan = os.path.join(d, "999999.sst")
    with open(orphan, "wb") as f:
        f.write(b"garbage that is not a run")
    db2 = VersionedLsm(d)
    assert not os.path.exists(orphan)
    assert db2.get(b"a", 1) == b"1"


def test_many_reopens_idempotent(tmp_path):
    d = str(tmp_path / "db")
    for cycle in range(5):
        db = VersionedLsm(d)
        v = cycle + 1
        db.apply(v, [(S, b"cycle", b"%d" % cycle)])
        db.flush()
        db.close()
    db = VersionedLsm(d)
    assert db.get(b"cycle", 10) == b"4"
    assert db.durable_version == 5
