"""ISSUE 15: limiter-driven elastic resolver recruitment + the
multi-resolver keyspace split + push-based rate updates.

Layers pinned here:

* the shared law's binding-limiter STREAK (the elasticity trigger's
  input) accumulates/resets correctly, including the fail-safe reset;
* `clip_transactions` (the proxy-side ResolutionRequestBuilder) is
  decision-identical to the pinned MultiResolverOracle semantics —
  phantom commits included;
* the controller's `_elastic_check` trigger semantics: fires only on a
  healthy resolver-shaped streak past the threshold, below the cap,
  exactly once per snapshot, with the elastic recovery reason;
* the controller's derived boundaries match the sharded kernel's
  canonical formula (the jax-free twin cannot drift);
* a REAL two-resolver wire pipeline with boundaries splits batches,
  min-combines verdicts, and keeps MVCC conflict semantics across and
  within partitions;
* push-based rate updates: hysteresis (`_push_due`) and the proxy-side
  apply path clearing staleness.
"""

import asyncio
import time

import pytest

from foundationdb_tpu.cluster import multiprocess as mp
from foundationdb_tpu.cluster.ratekeeper import AdmissionController
from foundationdb_tpu.models.types import CommitTransaction
from foundationdb_tpu.wire.codec import Mutation


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# the law's binding streak


def _slots(occ=0.0, queue=0):
    return {
        "tlogs": {}, "storages": {},
        "resolvers": {"resolver0": {"occupancy": occ,
                                    "queue_depth": queue}},
        "proxies": {},
    }


def test_binding_streak_accumulates_and_resets():
    law = AdmissionController(clock=time.monotonic, max_tps=1000.0)
    for i in range(3):
        law.update(_slots(occ=2.0), current_tps=500.0)
    assert law.binding_streak == {"name": "resolver_busy", "intervals": 3}
    # limiter releases -> budget eventually recovers to max ->
    # workload becomes binding and the resolver streak RESETS
    for _ in range(40):
        law.update(_slots(occ=0.0), current_tps=500.0)
    assert law.binding_streak["name"] == "workload"
    law.update(_slots(occ=2.0), current_tps=500.0)
    assert law.binding_streak == {"name": "resolver_busy", "intervals": 1}


def test_binding_streak_failsafe_resets():
    law = AdmissionController(clock=time.monotonic, max_tps=1000.0)
    law.update(_slots(occ=2.0), current_tps=500.0)
    law.decay()  # stale feed
    assert law.binding_streak["name"] == "ratekeeper_failsafe"
    info = law.rate_info()
    assert info["binding_streak"]["name"] == "ratekeeper_failsafe"


# ---------------------------------------------------------------------------
# boundaries + the clip


def test_controller_boundaries_match_sharding_formula():
    from foundationdb_tpu.parallel.sharding import default_boundaries

    for n in range(1, 9):
        assert mp.default_resolver_boundaries(n) == default_boundaries(n)
    with pytest.raises(ValueError):
        mp.default_resolver_boundaries(0)


def test_resolver_key_ranges_shape():
    assert mp.resolver_key_ranges([]) == [(b"", None)]
    assert mp.resolver_key_ranges([b"\x80"]) == [
        (b"", b"\x80"), (b"\x80", None),
    ]


def _txn(reads=(), writes=(), snap=0, report=False):
    return CommitTransaction(
        read_conflict_ranges=list(reads),
        write_conflict_ranges=list(writes),
        read_snapshot=snap,
        report_conflicting_keys=report,
    )


def test_clip_preserves_slot_alignment_and_clips_ranges():
    txns = [
        _txn(reads=[(b"\x10", b"\x20")]),           # low only
        _txn(writes=[(b"\xf0", b"\xf8")]),          # high only
        _txn(reads=[(b"\x70", b"\x90")]),           # straddles 0x80
    ]
    views = [
        mp.clip_transactions(txns, lo, hi)
        for lo, hi in mp.resolver_key_ranges([b"\x80"])
    ]
    low, high = views
    assert len(low) == len(high) == 3  # slots aligned
    assert low[0].read_conflict_ranges == [(b"\x10", b"\x20")]
    assert high[0].read_conflict_ranges == []
    assert low[1].write_conflict_ranges == []
    assert high[1].write_conflict_ranges == [(b"\xf0", b"\xf8")]
    assert low[2].read_conflict_ranges == [(b"\x70", b"\x80")]
    assert high[2].read_conflict_ranges == [(b"\x80", b"\x90")]


@pytest.mark.parametrize("seed", range(3))
def test_clip_min_combine_matches_multi_resolver_oracle(seed):
    """The proxy-side clip + per-partition resolve + min-combine IS the
    MultiResolverOracle's semantics (phantom commits included): random
    conflicting streams decide identically."""
    import numpy as np

    from foundationdb_tpu.testing.oracle import (
        ConflictOracle,
        MultiResolverOracle,
        OracleTxn,
    )

    rng = np.random.default_rng(seed)
    boundaries = [b"\x55", b"\xaa"]
    oracle = MultiResolverOracle(boundaries, window=10_000)
    shards = [ConflictOracle(10_000) for _ in range(3)]
    ranges = mp.resolver_key_ranges(boundaries)

    def rand_range():
        b = bytes([int(rng.integers(0, 250)), int(rng.integers(0, 250))])
        return (b, b + bytes([int(rng.integers(1, 60))]))

    version = 1000
    for _batch in range(6):
        version += 100
        txns = [
            _txn(
                reads=[rand_range() for _ in range(int(rng.integers(0, 3)))],
                writes=[rand_range() for _ in range(int(rng.integers(1, 3)))],
                snap=int(rng.integers(version - 300, version)),
            )
            for _ in range(8)
        ]
        want = oracle.resolve(
            [
                OracleTxn(
                    read_conflict_ranges=t.read_conflict_ranges,
                    write_conflict_ranges=t.write_conflict_ranges,
                    read_snapshot=t.read_snapshot,
                )
                for t in txns
            ],
            version,
        ).verdicts
        # the wire path's shape: clip per partition, resolve per
        # shard, min-combine
        got = [min(vs) for vs in zip(*(
            shard.resolve(
                [
                    OracleTxn(
                        read_conflict_ranges=v.read_conflict_ranges,
                        write_conflict_ranges=v.write_conflict_ranges,
                        read_snapshot=v.read_snapshot,
                    )
                    for v in mp.clip_transactions(txns, lo, hi)
                ],
                version,
            ).verdicts
            for shard, (lo, hi) in zip(shards, ranges)
        ))]
        assert got == want


# ---------------------------------------------------------------------------
# the elasticity trigger


def _controller(**conf):
    base = {"resolvers": 1, "elastic": True, "elastic_streak": 3,
            "elastic_max_resolvers": 2}
    base.update(conf)
    return mp.ClusterControllerRole(base)


def _armed(ctrl, *, name="resolver_busy", intervals=5, stale=False):
    ctrl._needs_recovery = False
    ctrl._rk_qos = {
        "binding_streak": {"name": name, "intervals": intervals},
        "budget_stale": stale,
    }


def test_elastic_trigger_fires_and_re_derives_topology():
    ctrl = _controller()
    _armed(ctrl)
    ctrl._elastic_check()
    assert ctrl.elastic_recruits == 1
    assert ctrl.conf["resolvers"] == 2
    assert ctrl._needs_recovery
    from foundationdb_tpu.cluster.generation import is_elastic_reason

    assert is_elastic_reason(ctrl._recovery_reason)
    assert ctrl._recovery_reason == "elastic:resolver->2"
    # the consumed snapshot cannot double-fire
    assert ctrl._rk_qos == {}
    # the supervision sleep is cut short like a pushed worker death —
    # the recruit starts next loop iteration, not check_interval later
    assert ctrl._wake.is_set()


def test_elastic_trigger_requires_streak():
    ctrl = _controller()
    _armed(ctrl, intervals=2)  # below elastic_streak=3
    ctrl._elastic_check()
    assert ctrl.elastic_recruits == 0 and not ctrl._needs_recovery


def test_elastic_trigger_ignores_stale_feed():
    ctrl = _controller()
    _armed(ctrl, stale=True)
    ctrl._elastic_check()
    assert ctrl.elastic_recruits == 0
    assert ctrl.elastic_last_streak == 0


def test_elastic_trigger_ignores_unrelated_limiters():
    ctrl = _controller()
    for name in ("workload", "log_server_write_queue",
                 "ratekeeper_failsafe"):
        _armed(ctrl, name=name)
        ctrl._elastic_check()
    assert ctrl.elastic_recruits == 0


def test_proxy_queue_limiter_recruits_a_proxy():
    """ISSUE 19: the SAME trigger machinery, routed by limiter name —
    a commit_proxy_queue streak recruits one more commit proxy, never
    a resolver."""
    ctrl = _controller()
    _armed(ctrl, name="commit_proxy_queue")
    ctrl._elastic_check()
    assert ctrl.elastic_recruits == 1
    assert ctrl.conf["proxies"] == 2
    assert ctrl.conf["resolvers"] == 1
    assert ctrl._recovery_reason == "elastic:proxy->2"
    # two proxies means scale-out mode: sequencer + partitioned chain
    assert ctrl._partitioned()
    # capped exactly like resolvers
    ctrl._needs_recovery = False
    _armed(ctrl, name="commit_proxy_queue", intervals=50)
    ctrl._elastic_check()
    assert ctrl.conf["proxies"] == 2


def test_workload_streak_scales_down_elastic_role():
    """ISSUE 19 satellite: a cold fleet — the law binding on "workload"
    for elastic_scale_down_streak intervals — retires ONE above-
    baseline elastic role through the same recovery walk."""
    ctrl = _controller(elastic_scale_down_streak=3)
    _armed(ctrl, name="commit_proxy_queue")
    ctrl._elastic_check()
    assert ctrl.conf["proxies"] == 2
    ctrl._needs_recovery = False
    _armed(ctrl, name="workload", intervals=2)  # below the streak
    ctrl._elastic_check()
    assert ctrl.elastic_scale_downs == 0 and not ctrl._needs_recovery
    _armed(ctrl, name="workload", intervals=3)
    ctrl._elastic_check()
    assert ctrl.elastic_scale_downs == 1
    assert ctrl.conf["proxies"] == 1
    assert ctrl._recovery_reason == "elastic:proxy->1"
    assert ctrl._needs_recovery and ctrl._wake.is_set()


def test_scale_down_never_cuts_below_declared_baseline():
    ctrl = _controller(resolvers=2, proxies=2,
                       elastic_scale_down_streak=2)
    _armed(ctrl, name="workload", intervals=10)
    ctrl._elastic_check()
    assert ctrl.elastic_scale_downs == 0
    assert ctrl.conf["resolvers"] == 2 and ctrl.conf["proxies"] == 2


def test_scale_down_gate_cannot_chain_retires():
    """The workload streak survives the retire's recovery walk like
    the recruit streak does: one retire per FRESH
    elastic_scale_down_streak intervals, never one per heartbeat."""
    ctrl = _controller(elastic_max_resolvers=3,
                       elastic_scale_down_streak=2)
    _armed(ctrl, intervals=3)
    ctrl._elastic_check()
    ctrl._needs_recovery = False
    _armed(ctrl, intervals=6)  # past the raised recruit gate (3+3)
    ctrl._elastic_check()
    assert ctrl.conf["resolvers"] == 3
    ctrl._needs_recovery = False
    _armed(ctrl, name="workload", intervals=2)
    ctrl._elastic_check()
    assert ctrl.conf["resolvers"] == 2
    ctrl._needs_recovery = False
    _armed(ctrl, name="workload", intervals=3)  # below gate (2+2)
    ctrl._elastic_check()
    assert ctrl.conf["resolvers"] == 2
    _armed(ctrl, name="workload", intervals=4)
    ctrl._elastic_check()
    assert ctrl.conf["resolvers"] == 1  # back to baseline, stops there


def test_persisted_topology_survives_controller_restart(tmp_path):
    """ISSUE 19 satellite: the planned elastic topology rides the
    state file next to the epoch — a kill -9'd controller restarts
    with the DECLARED conf and re-applies the persisted counts (but
    the scale-down baseline stays the declared one)."""
    sf = str(tmp_path / "controller_state.json")
    ctrl = mp.ClusterControllerRole(
        {"resolvers": 1, "elastic": True, "elastic_streak": 3,
         "elastic_max_resolvers": 2}, state_file=sf)
    _armed(ctrl, name="commit_proxy_queue")
    ctrl._elastic_check()
    assert ctrl.conf["proxies"] == 2
    ctrl._persist_epoch(7)  # what the recovery walk does first
    ctrl2 = mp.ClusterControllerRole(
        {"resolvers": 1, "elastic": True}, state_file=sf)
    assert ctrl2.conf["proxies"] == 2
    assert ctrl2._elastic_baseline["proxies"] == 1
    assert ctrl2.gen.epoch >= 7


def test_elastic_trigger_capped_and_disabled():
    ctrl = _controller(resolvers=2)  # already at the cap
    _armed(ctrl)
    ctrl._elastic_check()
    assert ctrl.elastic_recruits == 0
    off = _controller(elastic=False)
    _armed(off)
    off._elastic_check()
    assert off.elastic_recruits == 0 and not off._needs_recovery


def test_elastic_trigger_skipped_during_recovery():
    ctrl = _controller()
    _armed(ctrl)
    ctrl._needs_recovery = True
    ctrl._recovery_reason = "proxy0"
    ctrl._elastic_check()
    assert ctrl.elastic_recruits == 0
    assert ctrl._recovery_reason == "proxy0"


def test_resolver_queue_limiter_also_triggers():
    ctrl = _controller()
    _armed(ctrl, name="resolver_queue")
    ctrl._elastic_check()
    assert ctrl.elastic_recruits == 1


def test_elastic_surviving_streak_cannot_chain_recruits():
    """The ratekeeper's law outlives the recovery walk with its streak
    intact: a still-binding limiter must hold for elastic_streak FRESH
    intervals before the NEXT recruit — never chain one recruit per
    heartbeat off the pre-recruit streak."""
    ctrl = _controller(elastic_max_resolvers=3)
    _armed(ctrl, intervals=5)
    ctrl._elastic_check()
    assert ctrl.elastic_recruits == 1 and ctrl.conf["resolvers"] == 2
    ctrl._needs_recovery = False  # recovery walk "completed"
    # the law's streak CONTINUED across the recovery (6, 7 = the very
    # next healthy heartbeats): below the raised gate (5 + 3 = 8)
    for intervals in (6, 7):
        _armed(ctrl, intervals=intervals)
        ctrl._elastic_check()
        assert ctrl.elastic_recruits == 1, (
            f"chained a recruit off the surviving streak at "
            f"{intervals} intervals"
        )
    # elastic_streak fresh intervals on top of the recruit-time streak:
    # the previous recruit demonstrably didn't help — recruit again
    _armed(ctrl, intervals=8)
    ctrl._elastic_check()
    assert ctrl.elastic_recruits == 2 and ctrl.conf["resolvers"] == 3


def test_elastic_streak_reset_restores_normal_gate():
    """A streak RESET observed after a recruit (limiter released and
    re-engaged) is a fresh signal: the normal threshold applies, not
    the raised post-recruit gate."""
    ctrl = _controller(elastic_max_resolvers=3)
    _armed(ctrl, intervals=10)
    ctrl._elastic_check()
    assert ctrl.elastic_recruits == 1  # gate now 10 + 3 = 13
    ctrl._needs_recovery = False
    _armed(ctrl, intervals=1)  # the law restarted its count
    ctrl._elastic_check()
    assert ctrl.elastic_recruits == 1
    _armed(ctrl, intervals=3)  # a fresh streak at the normal threshold
    ctrl._elastic_check()
    assert ctrl.elastic_recruits == 2


# ---------------------------------------------------------------------------
# push-based rate updates


def _rk(**kw):
    return mp.RatekeeperRole([], **kw)


def test_push_due_hysteresis():
    rk = _rk()
    assert rk._push_due()  # nothing delivered yet
    info = rk.law.rate_info()
    rk._last_pushed = {
        "budget": info["transactions_per_second_limit"],
        "limiter": info["budget_limited_by"]["name"],
        "stale": bool(info["budget_stale"]),
    }
    assert not rk._push_due()  # unchanged: no push
    # a small drift stays inside the hysteresis band
    rk.law.tps_budget = rk._last_pushed["budget"] * (
        1.0 - rk.push_threshold / 2
    )
    assert not rk._push_due()
    # a large move pushes
    rk.law.tps_budget = rk._last_pushed["budget"] * 0.5
    assert rk._push_due()
    # a limiter flip pushes even at the same budget
    rk.law.tps_budget = rk._last_pushed["budget"]
    rk.law.limited_by = dict(rk.law.limited_by, name="resolver_busy")
    assert rk._push_due()


def test_proxy_rate_update_applies_and_clears_staleness(tmp_path):
    """A pushed GetRateInfo payload lands on the pipeline like a fresh
    poll: limit applied, staleness cleared, push counted."""
    import json

    class _Conn:  # enough of RpcConnection for construction
        pass

    pipe = mp.ProxyPipeline([_Conn()], _Conn(), _Conn(),
                            ratekeeper=_Conn())
    pipe._rate_stale = True
    pipe._rate_failures = 2
    role = mp.ProxyRole.__new__(mp.ProxyRole)
    role.pipeline = pipe
    role.epoch = 0
    role.stale_rate_pushes = 0
    law = AdmissionController(clock=time.monotonic, max_tps=5000.0)
    law.tps_budget = 123.0
    reply = run(role.rate_update(
        mp.RateUpdate(payload=json.dumps(law.rate_info()))
    ))
    assert json.loads(reply.payload)["ok"]
    assert pipe._rate_limit == 123.0
    assert not pipe._rate_stale and pipe._rate_failures == 0
    assert pipe.rate_pushes_applied == 1


def test_rate_push_epoch_fenced():
    """A superseded-but-alive ratekeeper's pushes are fenced BY EPOCH
    like every other control frame: a mismatched stamp is rejected
    retryably and the live budget (and fail-safe staleness state) is
    untouched."""
    import json

    from foundationdb_tpu.cluster.generation import is_stale_epoch
    from foundationdb_tpu.wire import transport

    class _Conn:
        pass

    pipe = mp.ProxyPipeline([_Conn()], _Conn(), _Conn(),
                            ratekeeper=_Conn(), epoch=3)
    pipe._rate_stale = True
    role = mp.ProxyRole.__new__(mp.ProxyRole)
    role.pipeline = pipe
    role.epoch = 3
    role.stale_rate_pushes = 0
    law = AdmissionController(clock=time.monotonic, max_tps=5000.0)
    law.tps_budget = 42.0
    stale = {**law.rate_info(), "epoch": 2}  # the OLD generation
    with pytest.raises(transport.RemoteError) as ei:
        run(role.rate_update(mp.RateUpdate(payload=json.dumps(stale))))
    assert is_stale_epoch(ei.value)
    assert role.stale_rate_pushes == 1
    assert pipe._rate_limit == float("inf")  # budget untouched
    assert pipe._rate_stale                  # staleness NOT cleared
    # the matching generation applies
    fresh = {**law.rate_info(), "epoch": 3}
    run(role.rate_update(mp.RateUpdate(payload=json.dumps(fresh))))
    assert pipe._rate_limit == 42.0 and not pipe._rate_stale


def test_rate_push_over_real_wire(tmp_path):
    """End to end over a UDS: a worker hosting a ProxyRole receives
    TOKEN_RATE_UPDATE and the hosted pipeline's budget moves — the
    exact frame the ratekeeper's _maybe_push_rate sends."""
    import json

    procs = [
        mp.spawn_role("resolver", str(tmp_path)),
        mp.spawn_role("tlog", str(tmp_path)),
        mp.spawn_role("storage", str(tmp_path)),
        mp.spawn_role("worker", str(tmp_path), worker_id="wpush"),
    ]
    try:
        async def scenario():
            worker = await mp.connect(procs[3].address)
            init = await worker.call(
                mp.TOKEN_INIT_ROLE,
                mp.InitializeRole(payload=json.dumps({
                    "kind": "proxy", "epoch": 0, "recover": False,
                    "topology": {
                        "resolvers": [procs[0].address],
                        "tlog": procs[1].address,
                        "storage": procs[2].address,
                    },
                })),
            )
            assert json.loads(init.payload)["ok"]
            law = AdmissionController(
                clock=time.monotonic, max_tps=5000.0
            )
            law.tps_budget = 77.0
            rep = await worker.call(
                mp.TOKEN_RATE_UPDATE,
                mp.RateUpdate(payload=json.dumps(law.rate_info())),
            )
            assert json.loads(rep.payload)["ok"]
            status = json.loads((await worker.call(
                mp.TOKEN_STATUS, mp.StatusRequest(pad=0)
            )).payload)
            grv = status["grv_proxy"]["qos"]
            assert grv["transactions_per_second_limit"] == 77.0
            assert grv["rate_pushes_applied"] == 1
            await worker.close()

        run(scenario())
    finally:
        for p in procs:
            p.stop()


# ---------------------------------------------------------------------------
# the split over a real two-resolver wire pipeline


def test_two_resolver_split_pipeline(tmp_path):
    """Boundaries split the batch: conflicts are detected inside each
    partition AND across the boundary (a straddling read clips into
    both), blind writes commit, and MVCC versioning holds."""
    procs = [
        mp.spawn_role("resolver", str(tmp_path), index=0),
        mp.spawn_role("resolver", str(tmp_path), index=1),
        mp.spawn_role("tlog", str(tmp_path)),
        mp.spawn_role("storage", str(tmp_path)),
    ]
    try:
        async def scenario():
            r0 = await mp.connect(procs[0].address)
            r1 = await mp.connect(procs[1].address)
            tlog = await mp.connect(procs[2].address)
            storage = await mp.connect(procs[3].address)
            pipe = mp.ProxyPipeline(
                [r0, r1], tlog, storage,
                resolver_boundaries=[b"\x80"],
            )
            pipe.start()
            lo_key, hi_key = b"\x10lo", b"\xf0hi"
            v1 = await pipe.commit(CommitTransaction(
                write_conflict_ranges=[(lo_key, lo_key + b"\x00")],
                mutations=[Mutation(0, lo_key, b"1")],
            ))
            v2 = await pipe.commit(CommitTransaction(
                write_conflict_ranges=[(hi_key, hi_key + b"\x00")],
                mutations=[Mutation(0, hi_key, b"2")],
            ))
            # stale reader in the LOW partition conflicts (only
            # resolver0 holds that history)
            with pytest.raises(mp.NotCommittedError):
                await pipe.commit(CommitTransaction(
                    read_conflict_ranges=[(lo_key, lo_key + b"\x00")],
                    read_snapshot=0,
                ))
            # stale reader in the HIGH partition conflicts too
            with pytest.raises(mp.NotCommittedError):
                await pipe.commit(CommitTransaction(
                    read_conflict_ranges=[(hi_key, hi_key + b"\x00")],
                    read_snapshot=0,
                ))
            # a stale read STRADDLING the boundary conflicts (either
            # side's clipped piece suffices)
            with pytest.raises(mp.NotCommittedError):
                await pipe.commit(CommitTransaction(
                    read_conflict_ranges=[(b"\x10", b"\xf1")],
                    read_snapshot=0,
                ))
            # fresh snapshots commit
            rv = await pipe.get_read_version()
            v3 = await pipe.commit(CommitTransaction(
                read_conflict_ranges=[(b"\x10", b"\xf1")],
                write_conflict_ranges=[(lo_key, lo_key + b"\x00")],
                read_snapshot=rv,
                mutations=[Mutation(0, lo_key, b"3")],
            ))
            assert v3 > v2 > v1
            assert await pipe.read(lo_key, v3) == b"3"
            assert await pipe.read(lo_key, v1) == b"1"
            assert await pipe.read(hi_key, v3) == b"2"
            await pipe.stop()
            for c in (r0, r1, tlog, storage):
                await c.close()

        run(scenario())
    finally:
        for p in procs:
            p.stop()


def test_boundary_count_validated():
    class _Conn:
        pass

    with pytest.raises(ValueError, match="boundary"):
        mp.ProxyPipeline([_Conn(), _Conn()], _Conn(), _Conn(),
                         resolver_boundaries=[b"\x40", b"\x80"])


# ---------------------------------------------------------------------------
# modeled compute locality


def test_local_txns_counts_partition_work():
    role = mp.ResolverRole.__new__(mp.ResolverRole)
    req = mp.ResolveTransactionBatchRequest(
        prev_version=-1, version=100, last_received_version=-1,
        transactions=[
            _txn(reads=[(b"a", b"b")]),
            _txn(),                       # clipped-out foreign slot
            _txn(writes=[(b"c", b"d")]),
        ],
    )
    assert role._local_txns(req) == 2
    from foundationdb_tpu.utils import packing
    from foundationdb_tpu.wire import codec

    creq = codec.ResolveBatchColumnar(
        prev_version=-1, version=100, last_received_version=-1,
        cols=packing.pack_columnar(req.transactions),
    )
    assert role._local_txns(creq) == 2
