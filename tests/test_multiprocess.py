"""Multi-process cluster: real OS processes over the serialized wire.

VERDICT r1 task 5's acceptance shape: client + proxy in this process,
resolver / tlog / storage as three child processes connected by UDS RPC
(the FlowTransport-analog), running a contended read-modify-write load
end-to-end with verdict, durability, and visibility semantics checked.
"""

import asyncio
import os

import pytest

from foundationdb_tpu.cluster import multiprocess as mp
from foundationdb_tpu.models.types import CommitTransaction
from foundationdb_tpu.wire import transport
from foundationdb_tpu.wire.codec import Mutation


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


@pytest.fixture
def cluster_procs(tmp_path):
    procs = [
        mp.spawn_role("resolver", str(tmp_path)),
        mp.spawn_role("tlog", str(tmp_path)),
        mp.spawn_role("storage", str(tmp_path)),
    ]
    yield procs
    for p in procs:
        p.stop()


def test_three_process_pipeline(cluster_procs):
    resolver_p, tlog_p, storage_p = cluster_procs

    async def scenario():
        resolver = await mp.connect(resolver_p.address)
        tlog = await mp.connect(tlog_p.address)
        storage = await mp.connect(storage_p.address)
        pipe = mp.ProxyPipeline([resolver], tlog, storage)
        pipe.start()

        # --- disjoint writes commit; stale read conflicts ---------------
        v1 = await pipe.commit(
            CommitTransaction(
                write_conflict_ranges=[(b"a", b"a\x00")],
                mutations=[Mutation(0, b"a", b"1")],
            )
        )
        assert v1 > 0
        # visibility: read-at-commit-version sees the write
        assert await pipe.read(b"a", v1) == b"1"

        rv = await pipe.get_read_version()
        assert rv >= v1

        # a second writer on the same key at a stale snapshot conflicts
        with pytest.raises(mp.NotCommittedError):
            await pipe.commit(
                CommitTransaction(
                    read_conflict_ranges=[(b"a", b"a\x00")],
                    write_conflict_ranges=[(b"a", b"a\x00")],
                    read_snapshot=0,  # before v1
                    mutations=[Mutation(0, b"a", b"2")],
                )
            )
        # at a current snapshot it commits
        v2 = await pipe.commit(
            CommitTransaction(
                read_conflict_ranges=[(b"a", b"a\x00")],
                write_conflict_ranges=[(b"a", b"a\x00")],
                read_snapshot=await pipe.get_read_version(),
                mutations=[Mutation(0, b"a", b"2")],
            )
        )
        assert v2 > v1
        assert await pipe.read(b"a", v2) == b"2"
        assert await pipe.read(b"a", v1) == b"1"  # MVCC: old version intact

        await pipe.stop()
        for c in (resolver, tlog, storage):
            await c.close()

    run(scenario())


def test_contended_counter_workload(cluster_procs):
    """YCSB-A-flavored: concurrent read-modify-writes on a small hot set;
    committed increments must equal the final counter values exactly."""
    resolver_p, tlog_p, storage_p = cluster_procs
    n_clients, n_ops, n_keys = 8, 15, 4

    async def scenario():
        resolver = await mp.connect(resolver_p.address)
        tlog = await mp.connect(tlog_p.address)
        storage = await mp.connect(storage_p.address)
        pipe = mp.ProxyPipeline([resolver], tlog, storage,
                                batch_interval=0.001)
        pipe.start()
        committed = [0] * n_keys

        async def client(cid: int):
            for i in range(n_ops):
                key = b"ctr%d" % ((cid + i) % n_keys)
                kr = (key, key + b"\x00")
                rv = await pipe.get_read_version()
                cur = await pipe.read(key, rv)
                n = int.from_bytes(cur or b"\0" * 8, "little")
                try:
                    await pipe.commit(
                        CommitTransaction(
                            read_conflict_ranges=[kr],
                            write_conflict_ranges=[kr],
                            read_snapshot=rv,
                            mutations=[
                                Mutation(0, key, (n + 1).to_bytes(8, "little"))
                            ],
                        )
                    )
                    committed[(cid + i) % n_keys] += 1
                except mp.NotCommittedError:
                    pass  # optimistic concurrency: retry-less client

        await asyncio.gather(*(client(c) for c in range(n_clients)))

        # consistency: final counters == exactly the committed increments
        rv = await pipe.get_read_version()
        snap = await storage.call(
            mp.TOKEN_STORAGE_SNAPSHOT, mp.StorageSnapshotReq(version=rv)
        )
        got = {k: int.from_bytes(v, "little") for k, v in snap.kvs}
        total_committed = sum(committed)
        assert total_committed > 0, "nothing committed — contention too high?"
        for i in range(n_keys):
            key = b"ctr%d" % i
            assert got.get(key, 0) == committed[i], (
                f"{key}: storage={got.get(key, 0)} committed={committed[i]}"
            )
        # under contention some conflicts must actually have happened for
        # this test to mean anything
        assert total_committed < n_clients * n_ops

        await pipe.stop()
        for c in (resolver, tlog, storage):
            await c.close()

    run(scenario())


def test_multi_resolver_min_combine(tmp_path):
    """Two resolver processes: the proxy min-combines verdicts
    (CommitProxyServer.actor.cpp:1551-1567) — a conflict on either
    resolver aborts the txn."""
    procs = [
        mp.spawn_role("resolver", str(tmp_path), index=0),
        mp.spawn_role("resolver", str(tmp_path), index=1),
        mp.spawn_role("tlog", str(tmp_path)),
        mp.spawn_role("storage", str(tmp_path)),
    ]
    try:
        async def scenario():
            r0 = await mp.connect(procs[0].address)
            r1 = await mp.connect(procs[1].address)
            tlog = await mp.connect(procs[2].address)
            storage = await mp.connect(procs[3].address)
            pipe = mp.ProxyPipeline([r0, r1], tlog, storage)
            pipe.start()
            v1 = await pipe.commit(
                CommitTransaction(
                    write_conflict_ranges=[(b"k", b"k\x00")],
                    mutations=[Mutation(0, b"k", b"v")],
                )
            )
            with pytest.raises(mp.NotCommittedError):
                await pipe.commit(
                    CommitTransaction(
                        read_conflict_ranges=[(b"k", b"k\x00")],
                        read_snapshot=0,
                    )
                )
            assert await pipe.read(b"k", v1) == b"v"
            await pipe.stop()
            for c in (r0, r1, tlog, storage):
                await c.close()

        run(scenario())
    finally:
        for p in procs:
            p.stop()


def test_stale_socket_unlinked_before_bind(tmp_path):
    """Satellite (kill -9 corpse): a role spawned on a socket path that
    already exists — the abandoned socket of a SIGKILLed predecessor —
    must unlink it before bind instead of crash-looping on EADDRINUSE
    (or leaving clients talking to the corpse)."""
    import socket

    stale_path = str(tmp_path / "resolver0.sock")
    # a REAL bound-then-abandoned unix socket (what kill -9 leaves): no
    # process behind it, the file present
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.bind(stale_path)
    s.close()
    assert os.path.exists(stale_path)

    proc = mp.spawn_role("resolver", str(tmp_path))
    try:
        async def scenario():
            conn = await mp.connect(proc.address)
            pong = await conn.call(mp.TOKEN_PING, mp.Ping(payload=b"alive"))
            assert pong.payload == b"alive"
            # the unlink is CORPSE-ONLY: binding over the LIVE role's
            # socket must refuse loudly, never silently hijack it
            thief = transport.RpcServer(proc.address)
            with pytest.raises(transport.TransportError, match="live"):
                await thief.start()
            # and the live role still serves
            pong = await conn.call(mp.TOKEN_PING, mp.Ping(payload=b"still"))
            assert pong.payload == b"still"
            await conn.close()

        run(scenario())
    finally:
        proc.stop()


def test_generation_fencing_over_uds(tmp_path):
    """Satellite (epoch fencing): a worker-hosted resolver recruited at
    epoch 2 accepts frames carrying epoch 2 and rejects a pre-recovery
    proxy's stale-epoch frame with the RETRYABLE stale_epoch error —
    both the columnar and the object resolve frames, and the tlog push,
    pinned in both directions over a real UDS."""
    import json

    from foundationdb_tpu.cluster.generation import is_stale_epoch
    from foundationdb_tpu.models.types import (
        ResolveTransactionBatchRequest,
        TransactionResult,
    )
    from foundationdb_tpu.utils import packing
    from foundationdb_tpu.wire import codec

    worker = mp.spawn_role("worker", str(tmp_path), worker_id="wfence")
    try:
        async def scenario():
            conn = await mp.connect(worker.address)
            for kind, spec in (("resolver", {}), ("tlog", {})):
                await conn.call(mp.TOKEN_INIT_ROLE, mp.InitializeRole(
                    payload=json.dumps({"kind": kind, "epoch": 2, **spec})
                ))

            txn = CommitTransaction(
                read_conflict_ranges=[(b"a", b"b")],
                write_conflict_ranges=[(b"a", b"b")],
                read_snapshot=0,
            )
            # fresh epoch, columnar frame: accepted (boot batch)
            rep = await conn.call(mp.TOKEN_RESOLVE, codec.ResolveBatchColumnar(
                prev_version=-1, version=100, last_received_version=-1,
                epoch=2, cols=packing.pack_columnar([txn]),
            ))
            assert rep.committed[0] == TransactionResult.COMMITTED
            # stale epoch, columnar frame: retryable rejection
            with pytest.raises(transport.RemoteError) as ei:
                await conn.call(mp.TOKEN_RESOLVE, codec.ResolveBatchColumnar(
                    prev_version=100, version=200,
                    last_received_version=100,
                    epoch=1, cols=packing.pack_columnar([txn]),
                ))
            assert is_stale_epoch(ei.value)
            # stale epoch, object frame: same rejection
            with pytest.raises(transport.RemoteError) as ei:
                await conn.call(mp.TOKEN_RESOLVE, ResolveTransactionBatchRequest(
                    prev_version=100, version=200,
                    last_received_version=100, epoch=1, transactions=[txn],
                ))
            assert is_stale_epoch(ei.value)
            # fresh epoch again: the chain advanced only by the accepted
            # batch — version 200 still free, accepted
            rep = await conn.call(mp.TOKEN_RESOLVE, codec.ResolveBatchColumnar(
                prev_version=100, version=200, last_received_version=100,
                epoch=2, cols=packing.pack_columnar([txn]),
            ))
            assert len(rep.committed) == 1

            # the tlog fence, both directions
            rep = await conn.call(mp.TOKEN_TLOG_PUSH, mp.TLogPush(
                version=10, prev_version=-1, mutations=[], epoch=2,
            ))
            assert rep.durable_version == 10
            with pytest.raises(transport.RemoteError) as ei:
                await conn.call(mp.TOKEN_TLOG_PUSH, mp.TLogPush(
                    version=20, prev_version=10, mutations=[], epoch=1,
                ))
            assert is_stale_epoch(ei.value)
            # the lock advances the fence and reports the durable version
            lock = await conn.call(
                mp.TOKEN_TLOG_LOCK, mp.TLogLock(epoch=3)
            )
            assert lock.durable_version == 10
            with pytest.raises(transport.RemoteError) as ei:
                await conn.call(mp.TOKEN_TLOG_PUSH, mp.TLogPush(
                    version=30, prev_version=10, mutations=[], epoch=2,
                ))
            assert is_stale_epoch(ei.value)
            # fencing is visible in status
            st = json.loads((await conn.call(
                mp.TOKEN_STATUS, mp.StatusRequest(pad=0)
            )).payload)
            assert st["role_epochs"] == {"resolver": 2, "tlog": 2}
            await conn.close()

        run(scenario())
    finally:
        worker.stop()


def test_legacy_tlog_wal_record_decodes(tmp_path):
    """On-disk compatibility: a tlog WAL record written BEFORE the
    epoch field (protocol 0007's 3-field TLogPush) must still replay —
    disk records are not version-gated by the wire handshake. Legacy
    records land at epoch 0; the recovery lock re-fences before any
    new-generation push."""
    from foundationdb_tpu.wire import codec

    out = codec.WriteBuffer()
    codec.w_u16(out, 0x0210)
    codec.w_i64(out, 42)       # version
    codec.w_i64(out, 41)       # prev_version
    codec.w_u32(out, 1)        # one mutation
    codec.w_mutation(out, Mutation(0, b"k", b"v"))
    legacy = out.getvalue()
    rec = mp._decode_tlog_record(legacy)
    assert (rec.version, rec.prev_version, rec.epoch) == (42, 41, 0)
    assert rec.mutations == [Mutation(0, b"k", b"v")]
    # and the current layout still round-trips through the same helper
    cur = codec.encode(mp.TLogPush(
        version=43, prev_version=42, mutations=[], epoch=7,
    ))
    assert mp._decode_tlog_record(cur).epoch == 7
    # garbage is still rejected
    with pytest.raises(codec.CodecError):
        mp._decode_tlog_record(legacy + b"\x00")


def test_tlog_pop_requires_durable_storage(tmp_path):
    """The applier pops the tlog ONLY on durable storage acks: with a
    memory-only store the tlog is the single durable copy of committed
    mutations, and popping it would lose them on a storage death (code
    review r13). With a WAL-backed store the pop engages and the log
    stays tail-sized."""
    import json

    for engine_dir, expect_popped in ((None, False), ("sdata", True)):
        sock = str(tmp_path / (engine_dir or "mem"))
        os.makedirs(sock, exist_ok=True)
        procs = [
            mp.spawn_role("resolver", sock),
            mp.spawn_role("tlog", sock, data_dir=os.path.join(sock, "tl")),
            mp.spawn_role(
                "storage", sock,
                data_dir=(
                    os.path.join(sock, engine_dir) if engine_dir else None
                ),
            ),
        ]
        try:
            async def scenario():
                resolver = await mp.connect(procs[0].address)
                tlog = await mp.connect(procs[1].address)
                storage = await mp.connect(procs[2].address)
                pipe = mp.ProxyPipeline([resolver], tlog, storage,
                                        batch_interval=0.001)
                pipe.start()
                for i in range(4):
                    await pipe.commit(CommitTransaction(
                        mutations=[Mutation(0, b"p%d" % i, b"v")],
                    ))
                await pipe.stop()
                st = json.loads((await tlog.call(
                    mp.TOKEN_STATUS, mp.StatusRequest(pad=0)
                )).payload)
                for c in (resolver, tlog, storage):
                    await c.close()
                return st["qos"]["entries"]

            entries = run(scenario())
            if expect_popped:
                assert entries < 4, f"durable store: tlog not popped ({entries})"
            else:
                assert entries == 4, f"memory store: tlog popped ({entries})"
        finally:
            for p in procs:
                p.stop()


def test_span_context_propagates_across_process_boundary(tmp_path):
    """ISSUE 5 wire acceptance: a traced commit batch's span context
    rides the UDS resolve request into the resolver OS PROCESS, whose
    child span (same trace id, parent edge) and Resolver.resolveBatch.*
    micro-events land in its --trace-file — commit_debug merges both
    processes' files into one cross-process timeline."""
    import json
    import time as _time

    from foundationdb_tpu.utils import commit_debug as cd
    from foundationdb_tpu.utils import spans as _spans
    from foundationdb_tpu.utils import trace as _tr

    res_trace = str(tmp_path / "resolver.jsonl")
    procs = [
        mp.spawn_role("resolver", str(tmp_path), trace_file=res_trace),
        mp.spawn_role("tlog", str(tmp_path)),
        mp.spawn_role("storage", str(tmp_path)),
    ]
    proxy_trace = str(tmp_path / "proxy.jsonl")
    sink = _tr.TraceLog(
        min_severity=_tr.SEV_DEBUG, clock=_time.time, path=proxy_trace
    )
    prev_sinks = _tr.install(
        sink, _tr.TraceBatch(clock=_time.time, logger=sink, enabled=True)
    )
    prev_exp = _spans.set_exporter(_spans.SpanExporter(trace_log=sink))
    try:
        async def scenario():
            resolver = await mp.connect(procs[0].address)
            tlog = await mp.connect(procs[1].address)
            storage = await mp.connect(procs[2].address)
            pipe = mp.ProxyPipeline(
                [resolver], tlog, storage, trace=True
            )
            pipe.start()
            txn = CommitTransaction(
                write_conflict_ranges=[(b"w", b"w\x00")],
                mutations=[Mutation(0, b"w", b"1")],
                debug_id="xproc-1",
            )
            _tr.g_trace_batch.add_event(
                "CommitDebug", "xproc-1", cd.COMMIT_BEFORE
            )
            v = await pipe.commit(txn)
            _tr.g_trace_batch.add_event(
                "CommitDebug", "xproc-1", cd.COMMIT_AFTER
            )
            assert v > 0
            await pipe.stop()
            for c in (resolver, tlog, storage):
                await c.close()

        run(scenario())
    finally:
        _tr.install(*prev_sinks)
        _spans.set_exporter(prev_exp)
        for p in procs:
            p.stop()

    proxy_recs = cd.load_jsonl([proxy_trace])
    res_recs = cd.load_jsonl([res_trace])
    # the child process exported a resolveBatch span chained to a trace
    # id minted in THIS process
    proxy_tids = {
        r["TraceID"] for r in proxy_recs if r["Type"] == "Span"
    }
    child_spans = [
        r for r in res_recs
        if r["Type"] == "Span"
        and r["Location"] == "Resolver.resolveBatch"
    ]
    assert child_spans
    assert any(
        s["TraceID"] in proxy_tids and s["ParentID"] for s in child_spans
    )
    # and the merged files reconstruct one cross-process timeline
    idx = cd.TraceIndex(proxy_recs + res_recs)
    (tl,) = idx.timelines()
    assert tl.debug_id == "xproc-1"
    locs = tl.locations()
    assert cd.RESOLVER_BEFORE in locs and cd.RESOLVER_AFTER in locs
    assert cd.TLOG_AFTER_COMMIT in locs and cd.STORAGE_APPLIED in locs
    assert json.dumps(tl.stage_durations())  # waterfall JSON-able
