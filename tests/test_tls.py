"""Mutual TLS on the wire transport (flow/TLSConfig.actor.cpp analog).

The reference's contract: with TLS configured, both sides present
CA-chained certificates; unverified peers are dropped at handshake and
never see a frame; verify_peers subject checks reject certs with the
wrong attributes even when CA-valid.
"""

import asyncio

import pytest

pytest.importorskip("cryptography")

from foundationdb_tpu.crypto.tls import TLSConfig, make_test_tls
from foundationdb_tpu.cluster.multiprocess import Ping, Pong
from foundationdb_tpu.wire import transport

TOKEN = 0x7777


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


async def _serve(address, tls):
    server = transport.RpcServer(address, tls=tls)

    async def ping(msg: Ping) -> Pong:
        return Pong(payload=msg.payload)

    server.register(TOKEN, ping)
    await server.start()
    return server


@pytest.mark.parametrize("kind", ["uds", "tcp"])
def test_mutual_tls_roundtrip(tmp_path, kind):
    tls = make_test_tls(str(tmp_path / "pki"))
    address = (
        str(tmp_path / "tls.sock") if kind == "uds" else ("127.0.0.1", 0)
    )

    async def go():
        server = await _serve(address, tls["server"])
        addr = (
            address if kind == "uds"
            else ("127.0.0.1", server._server.sockets[0].getsockname()[1])
        )
        conn = transport.RpcConnection(addr, tls=tls["client"])
        await conn.connect()
        rep = await conn.call(TOKEN, Ping(payload=b"over-tls"))
        assert rep.payload == b"over-tls"
        await conn.close()
        await server.close()

    run(go())


def test_plaintext_client_rejected(tmp_path):
    """A client without TLS never completes a handshake with a TLS
    server — the connection dies before any frame is served."""
    tls = make_test_tls(str(tmp_path / "pki"))
    address = str(tmp_path / "tls.sock")

    async def go():
        server = await _serve(address, tls["server"])
        conn = transport.RpcConnection(address)  # no TLS
        with pytest.raises(transport.TransportError):
            await conn.connect(retries=2, delay=0.01)
        await conn.close()
        await server.close()

    run(go())


def test_client_without_cert_rejected(tmp_path):
    """Mutual TLS: the server requires a CA-chained CLIENT cert; a
    client trusting the CA but presenting no certificate is dropped."""
    import ssl as _ssl

    tls = make_test_tls(str(tmp_path / "pki"))
    address = str(tmp_path / "tls.sock")

    async def go():
        server = await _serve(address, tls["server"])
        ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_verify_locations(tls["client"].ca_file)
        ctx.check_hostname = False
        try:
            reader, writer = await asyncio.open_unix_connection(
                path=address, ssl=ctx, server_hostname=""
            )
            # server may only discover the missing cert at first read
            writer.write(b"x" * 64)
            await writer.drain()
            data = await asyncio.wait_for(reader.read(16), timeout=2)
            assert data == b""  # server hung up without serving
        except (_ssl.SSLError, ConnectionError, asyncio.IncompleteReadError):
            pass  # equally acceptable: dropped during handshake
        await server.close()

    run(go())


def test_wrong_ca_rejected(tmp_path):
    """A cert chained to a DIFFERENT CA fails verification even though
    it is structurally valid."""
    tls_a = make_test_tls(str(tmp_path / "pki_a"))
    tls_b = make_test_tls(str(tmp_path / "pki_b"))
    address = str(tmp_path / "tls.sock")

    async def go():
        server = await _serve(address, tls_a["server"])
        # client presents pki_b's cert but trusts pki_a's CA: the
        # SERVER refuses the client cert (mutual verification)
        mixed = TLSConfig(
            ca_file=tls_a["client"].ca_file,
            cert_file=tls_b["client"].cert_file,
            key_file=tls_b["client"].key_file,
        )
        conn = transport.RpcConnection(address, tls=mixed)
        with pytest.raises(transport.TransportError):
            await conn.connect(retries=2, delay=0.01)
        await conn.close()
        await server.close()

    run(go())


def test_verify_peer_organization(tmp_path):
    """The verify_peers-style subject check: a CA-valid peer with the
    wrong O= is refused AFTER the TLS handshake, before any frame."""
    tls = make_test_tls(str(tmp_path / "pki"), organization="good-org")
    address = str(tmp_path / "tls.sock")

    async def go():
        server = await _serve(address, tls["server"])
        ok = TLSConfig(
            ca_file=tls["client"].ca_file,
            cert_file=tls["client"].cert_file,
            key_file=tls["client"].key_file,
            verify_peer_organization="good-org",
        )
        conn = transport.RpcConnection(address, tls=ok)
        await conn.connect()
        rep = await conn.call(TOKEN, Ping(payload=b"x"))
        assert rep.payload == b"x"
        await conn.close()

        bad = TLSConfig(
            ca_file=tls["client"].ca_file,
            cert_file=tls["client"].cert_file,
            key_file=tls["client"].key_file,
            verify_peer_organization="other-org",
        )
        conn2 = transport.RpcConnection(address, tls=bad)
        with pytest.raises(transport.TransportError):
            await conn2.connect(retries=1, delay=0.01)
        await conn2.close()
        await server.close()

    run(go())


def test_server_side_verify_peers_rejects_wrong_org(tmp_path):
    """Server-side verify_peers: a client under the same CA but the
    wrong organization is dropped before any frame is served."""
    from foundationdb_tpu.crypto.tls import generate_ca, issue_cert

    pki = str(tmp_path / "pki")
    ca_cert, ca_key = generate_ca(pki, organization="good-org")
    s_cert, s_key = issue_cert(pki, ca_cert, ca_key, "server",
                               organization="good-org")
    c_cert, c_key = issue_cert(pki, ca_cert, ca_key, "rogue",
                               organization="rogue-org")
    address = str(tmp_path / "tls.sock")

    async def go():
        server_tls = TLSConfig(
            ca_file=ca_cert, cert_file=s_cert, key_file=s_key,
            verify_peer_organization="good-org",
        )
        server = await _serve(address, server_tls)
        rogue = TLSConfig(ca_file=ca_cert, cert_file=c_cert, key_file=c_key)
        conn = transport.RpcConnection(address, tls=rogue)
        # the TLS handshake itself succeeds (CA-valid cert); the
        # server's subject check then drops the connection, so the
        # client dies at the transport handshake or first call
        try:
            await conn.connect(retries=1, delay=0.01)
            with pytest.raises(
                (transport.TransportError, asyncio.TimeoutError)
            ):
                await conn.call(TOKEN, Ping(payload=b"x"), timeout=1.0)
        except (transport.TransportError, ConnectionError):
            pass
        await conn.close()
        await server.close()

    run(go())


def test_multiprocess_cluster_over_tls(tmp_path, monkeypatch):
    """Full cluster with FDB_TPU_TLS_DIR: every role serves mutual TLS,
    the pipeline commits and reads through it, and a plaintext client
    is refused — the reference's cluster-wide TLS mode."""
    import os

    from foundationdb_tpu.cluster import multiprocess as mp
    from foundationdb_tpu.crypto.tls import make_test_tls
    from foundationdb_tpu.models.types import CommitTransaction

    pki = str(tmp_path / "pki")
    tls = make_test_tls(pki, names=("node",))
    # the conventional layout _tls_from_env expects
    assert os.path.exists(os.path.join(pki, "ca.crt"))
    monkeypatch.setenv("FDB_TPU_TLS_DIR", pki)

    socket_dir = str(tmp_path / "socks")
    os.makedirs(socket_dir)
    roles = []
    try:
        tlog = mp.spawn_role("tlog", socket_dir)
        storage = mp.spawn_role("storage", socket_dir)
        resolver = mp.spawn_role("resolver", socket_dir, backend="native")
        roles = [tlog, storage, resolver]

        async def go():
            rc = await mp.connect(resolver.address)
            tc = await mp.connect(tlog.address)
            sc = await mp.connect(storage.address)
            pipe = mp.ProxyPipeline([rc], tc, sc)
            pipe.start()
            try:
                v = await pipe.commit(CommitTransaction(
                    read_conflict_ranges=[], write_conflict_ranges=[],
                    mutations=[(0, b"tlsk", b"tlsv")], read_snapshot=0,
                ))
                assert await pipe.read(b"tlsk", v) == b"tlsv"
            finally:
                await pipe.stop()
                for c in (rc, tc, sc):
                    await c.close()

            # plaintext client refused by the TLS cluster
            plain = transport.RpcConnection(storage.address)  # no tls
            with pytest.raises(transport.TransportError):
                await plain.connect(retries=2, delay=0.01)
            await plain.close()

        run(go())
    finally:
        for r in roles:
            r.stop()
