"""Group kernel parity: resolve_group(G batches) == G x resolve_batch.

The group kernel (ops/group.py) must be decision-identical to resolving
the same batches sequentially — including the hard part: a read's
snapshot can land BETWEEN the group's commit versions, so its conflicts
with earlier in-group batches are version-dependent, exactly as if
those batches had already merged into history.

Also asserts the final history STATE is semantically identical (same
piecewise key->version map; boundary arrays may differ in redundant
rows, so maps are compared by evaluation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from foundationdb_tpu.config import KernelConfig
from foundationdb_tpu.models.types import CommitTransaction
from foundationdb_tpu.ops import conflict as C
from foundationdb_tpu.ops import group as G
from foundationdb_tpu.ops import history as H
from foundationdb_tpu.utils import packing

from conftest import random_key, random_range

# compile-heavy kernel tests: run with -m kernel (fast lane: -m 'not kernel')
pytestmark = pytest.mark.kernel


def small_config(**kw):
    defaults = dict(
        max_key_bytes=8,
        max_txns=16,
        max_reads=32,
        max_writes=32,
        history_capacity=512,
        window_versions=1000,
    )
    defaults.update(kw)
    return KernelConfig(**defaults)


def random_txn(rng, *, n_ranges=2, snap_lo, snap_hi, blind_prob=0.15):
    reads = [] if rng.random() < blind_prob else [
        random_range(rng) for _ in range(1 + int(rng.integers(0, n_ranges)))
    ]
    writes = [random_range(rng) for _ in range(1 + int(rng.integers(0, n_ranges)))]
    return CommitTransaction(
        read_conflict_ranges=reads,
        write_conflict_ranges=writes,
        read_snapshot=int(rng.integers(snap_lo, snap_hi)),
    )


def gen_group(rng, config, g, base_version=1000, step=100, n_txns=12):
    """G batches whose snapshots deliberately straddle the group's
    commit versions (the cross-batch visibility trap)."""
    batches = []
    for i in range(g):
        version = base_version + (i + 1) * step
        txns = [
            random_txn(
                rng,
                snap_lo=max(0, base_version - 2 * step),
                snap_hi=version,  # exclusive: snap < own commit version
            )
            for _ in range(n_txns)
        ]
        batches.append(
            packing.pack_batch(txns, version, 0, config)
        )
    return batches


def canonical_map(state, config):
    """(boundary bytes, version) pairs with redundant rows collapsed."""
    mk = np.asarray(state.main_keys)
    mv = np.asarray(state.main_ver)
    rows = []
    for j in range(mk.shape[0]):
        if all(x == 0xFFFFFFFF for x in mk[j]):
            continue
        rows.append((tuple(mk[j]), int(mv[j])))
    rows.sort()
    # collapse equal-key rows (keep last = value in force) and
    # value-repeats (redundant boundaries)
    dedup = {}
    for k, v in rows:
        dedup[k] = v  # later rows (same key) overwrite: sorted order keeps last
    out = []
    for k in sorted(dedup):
        if not out or out[-1][1] != dedup[k]:
            out.append((k, dedup[k]))
    return out


def run_sequential(config, batches):
    state = H.init(config)
    step = jax.jit(C.resolve_batch)
    outs = []
    for pb in batches:
        state, out = step(state, pb.device_args())
        outs.append(jax.tree_util.tree_map(np.asarray, out))
    return state, outs


def run_group(config, batches):
    state = H.init(config)
    stacked = packing.stack_device_args(batches)
    state, out = jax.jit(G.resolve_group)(state, stacked)
    return state, jax.tree_util.tree_map(np.asarray, out)


def assert_group_matches(config, batches):
    s_seq, seq_outs = run_sequential(config, batches)
    s_grp, grp_out = run_group(config, batches)
    for i, so in enumerate(seq_outs):
        np.testing.assert_array_equal(
            grp_out.verdict[i], so.verdict, err_msg=f"verdict batch {i}"
        )
        np.testing.assert_array_equal(
            grp_out.hist_conflict_read[i],
            so.hist_conflict_read,
            err_msg=f"hist_conflict_read batch {i}",
        )
        np.testing.assert_array_equal(
            grp_out.intra_first_range[i],
            so.intra_first_range,
            err_msg=f"intra_first_range batch {i}",
        )
        assert grp_out.committed_count[i] == so.committed_count
        assert grp_out.too_old_count[i] == so.too_old_count
    assert canonical_map(s_grp, config) == canonical_map(s_seq, config), (
        "final history maps diverge"
    )


@pytest.mark.parametrize("seed", range(8))
def test_group_matches_sequential_random(seed):
    rng = np.random.default_rng(seed)
    config = small_config()
    batches = gen_group(rng, config, g=4)
    assert_group_matches(config, batches)


def test_group_snapshot_straddles_versions():
    """A read whose snapshot >= an earlier group batch's version must NOT
    conflict with that batch's writes (it already saw them)."""
    config = small_config()
    k = lambda i: bytes([i])
    t_writer = CommitTransaction(
        read_conflict_ranges=[],
        write_conflict_ranges=[(k(5), k(6))],
        read_snapshot=50,
    )
    # snapshot 150 >= batch-0 version 100: writer already visible
    t_reader_new = CommitTransaction(
        read_conflict_ranges=[(k(5), k(6))],
        write_conflict_ranges=[(k(9), k(10))],
        read_snapshot=150,
    )
    # snapshot 90 < 100: conflict
    t_reader_old = CommitTransaction(
        read_conflict_ranges=[(k(5), k(6))],
        write_conflict_ranges=[(k(11), k(12))],
        read_snapshot=90,
    )
    b0 = packing.pack_batch([t_writer], 100, 0, config)
    b1 = packing.pack_batch([t_reader_new, t_reader_old], 200, 0, config)
    assert_group_matches(config, [b0, b1])
    _, out = run_group(config, [b0, b1])
    assert out.verdict[1][0] == C.COMMITTED  # saw the write already
    assert out.verdict[1][1] == C.CONFLICT   # stale snapshot


def test_group_too_old_and_blind_writes():
    config = small_config(window_versions=100)
    k = lambda i: bytes([i])
    stale = CommitTransaction(
        read_conflict_ranges=[(k(1), k(2))],
        write_conflict_ranges=[(k(1), k(2))],
        read_snapshot=5,
    )
    blind = CommitTransaction(
        read_conflict_ranges=[],
        write_conflict_ranges=[(k(3), k(4))],
        read_snapshot=5,  # stale snapshot but NO reads: never too old
    )
    b0 = packing.pack_batch([stale, blind], 200, 0, config)
    b1 = packing.pack_batch([stale], 300, 0, config)
    assert_group_matches(config, [b0, b1])
    _, out = run_group(config, [b0, b1])
    assert out.verdict[0][0] == C.TOO_OLD
    assert out.verdict[0][1] == C.COMMITTED


@pytest.mark.parametrize("seed", range(4))
def test_group_hot_key_contention(seed):
    """Zipf-style: every batch reads+writes one hot range — long
    cross-batch conflict chains exercise the fixpoint depth."""
    rng = np.random.default_rng(100 + seed)
    config = small_config()
    hot = (b"\x10", b"\x11")
    batches = []
    base, step = 1000, 100
    for i in range(4):
        version = base + (i + 1) * step
        txns = []
        for _t in range(8):
            txns.append(CommitTransaction(
                read_conflict_ranges=[hot] if rng.random() < 0.7 else [random_range(rng)],
                write_conflict_ranges=[hot] if rng.random() < 0.7 else [random_range(rng)],
                read_snapshot=int(rng.integers(base - step, version)),
            ))
        batches.append(packing.pack_batch(txns, version, 0, config))
    assert_group_matches(config, batches)


def test_group_continuation_across_groups():
    """State threads between groups: group 2 must see group 1's writes
    as ordinary history."""
    rng = np.random.default_rng(7)
    config = small_config()
    all_batches = gen_group(rng, config, g=6, n_txns=10)
    s_seq, seq_outs = run_sequential(config, all_batches)

    state = H.init(config)
    jg = jax.jit(G.resolve_group)
    outs = []
    for lo in (0, 3):
        stacked = packing.stack_device_args(all_batches[lo : lo + 3])
        state, out = jg(state, stacked)
        outs.append(jax.tree_util.tree_map(np.asarray, out))
    for i in range(6):
        np.testing.assert_array_equal(
            outs[i // 3].verdict[i % 3],
            seq_outs[i].verdict,
            err_msg=f"batch {i}",
        )
    assert canonical_map(state, config) == canonical_map(s_seq, config)


@pytest.mark.parametrize("seed", range(4))
def test_group_parity_with_prestate(seed):
    """Parity — including the per-read hist_conflict_read report — when
    history is NON-empty before the group (a txn condemned by pre-group
    history must still report its cross-batch conflicting reads)."""
    rng = np.random.default_rng(200 + seed)
    config = small_config()
    pre = gen_group(rng, config, g=2, base_version=500)
    batches = gen_group(rng, config, g=4, base_version=1000)

    state_a = H.init(config)
    step = jax.jit(C.resolve_batch)
    for pb in pre:
        state_a, _ = step(state_a, pb.device_args())
    seq_outs = []
    state_s = state_a
    for pb in batches:
        state_s, out = step(state_s, pb.device_args())
        seq_outs.append(jax.tree_util.tree_map(np.asarray, out))

    state_b = H.init(config)
    for pb in pre:
        state_b, _ = step(state_b, pb.device_args())
    stacked = packing.stack_device_args(batches)
    state_g, grp = jax.jit(G.resolve_group)(state_b, stacked)
    grp = jax.tree_util.tree_map(np.asarray, grp)

    for i, so in enumerate(seq_outs):
        np.testing.assert_array_equal(grp.verdict[i], so.verdict)
        np.testing.assert_array_equal(
            grp.hist_conflict_read[i], so.hist_conflict_read,
            err_msg=f"hist_conflict_read batch {i}",
        )
        np.testing.assert_array_equal(
            grp.intra_first_range[i], so.intra_first_range
        )
    assert canonical_map(state_g, config) == canonical_map(state_s, config)


@pytest.mark.parametrize("seed", range(4))
def test_short_span_path_matches_general(seed):
    """short_span_limit=S compiles the direct range ops; on workloads
    within the span bound it must be decision-identical to the general
    path, with no latch trip."""
    import functools

    rng = np.random.default_rng(300 + seed)
    config = small_config()
    # point-ish ranges: single-byte keys, [k, k+1) style
    def point_txn():
        k = bytes([int(rng.integers(0, 40))])
        k2 = bytes([int(rng.integers(0, 40))])
        return CommitTransaction(
            read_conflict_ranges=[(k, k + b"\x01")],
            write_conflict_ranges=[(k2, k2 + b"\x01")],
            read_snapshot=int(rng.integers(900, 1100 + 100 * rng.integers(1, 3))),
        )

    batches = [
        packing.pack_batch(
            [point_txn() for _ in range(10)], 1000 + (i + 1) * 100, 0, config
        )
        for i in range(3)
    ]
    stacked = packing.stack_device_args(batches)

    s0, out0 = jax.jit(G.resolve_group)(H.init(config), stacked)
    jf = jax.jit(functools.partial(G.resolve_group, short_span_limit=8))
    s1, out1 = jf(H.init(config), stacked)
    np.testing.assert_array_equal(
        np.asarray(out1.verdict), np.asarray(out0.verdict)
    )
    np.testing.assert_array_equal(
        np.asarray(out1.hist_conflict_read), np.asarray(out0.hist_conflict_read)
    )
    assert not bool(np.asarray(out1.overflow).any()), "latch must not trip"
    assert canonical_map(s1, config) == canonical_map(s0, config)


def test_short_span_latch_trips_on_wide_ranges():
    """A range wider than the limit must trip the loud latch (overflow),
    never silently resolve."""
    import functools

    config = small_config()
    wide = CommitTransaction(
        read_conflict_ranges=[(b"\x00", b"\x30")],  # spans many keys
        write_conflict_ranges=[
            (bytes([i]), bytes([i]) + b"\x01") for i in range(12)
        ],
        read_snapshot=1000,
    )
    b0 = packing.pack_batch([wide], 1100, 0, config)
    jf = jax.jit(functools.partial(G.resolve_group, short_span_limit=2))
    _s, out = jf(H.init(config), packing.stack_device_args([b0]))
    assert bool(np.asarray(out.overflow).any())


def test_group_of_one_equals_resolve_batch():
    rng = np.random.default_rng(3)
    config = small_config()
    batches = gen_group(rng, config, g=1)
    assert_group_matches(config, batches)


def test_fixpoint_latch_refuses_deep_chains_and_preserves_state():
    """fixpoint_latch mode: convergence is checked, not assumed. A
    conflict chain deeper than the unroll trips GroupVerdict.unconverged
    and the state comes back UNCHANGED; with enough unroll the decisions
    are identical to the exact while-loop kernel."""
    import jax
    import functools

    import numpy as np

    from foundationdb_tpu.config import TEST_CONFIG
    from foundationdb_tpu.models.types import CommitTransaction
    from foundationdb_tpu.ops import group as G
    from foundationdb_tpu.ops import history as H
    from foundationdb_tpu.utils import packing

    # a LONG alternating chain: txn i reads key[i-1] and writes key[i]
    # with distinct keys -> committed/conflicted alternates, chain depth
    # ~B (the worst case for a bounded unroll)
    n = 12
    txns = []
    for i in range(n):
        k_prev = b"ch%02d" % (i - 1) if i else b"zz"
        k = b"ch%02d" % i
        txns.append(CommitTransaction(
            read_conflict_ranges=[(k_prev, k_prev + b"\x00")],
            write_conflict_ranges=[(k, k + b"\x00")],
            read_snapshot=5,
        ))
    batch = packing.pack_batch(txns, 10, 0, TEST_CONFIG)
    stacked = packing.stack_device_args([batch])

    def run(latch, unroll):
        state = H.init(TEST_CONFIG)
        fn = jax.jit(functools.partial(
            G.resolve_group, fixpoint_unroll=unroll, fixpoint_latch=latch
        ))
        st2, out = fn(state, stacked)
        return state, st2, out

    # exact kernel: ground truth
    _, st_exact, out_exact = run(latch=False, unroll=2)
    assert not bool(np.asarray(out_exact.unconverged).any())

    # latch kernel, too-shallow unroll: refuses, state unchanged
    st0, st_l, out_l = run(latch=True, unroll=2)
    assert bool(np.asarray(out_l.unconverged).all())
    assert (np.asarray(st_l.main_ver) == np.asarray(st0.main_ver)).all()
    assert (np.asarray(st_l.main_keys) == np.asarray(st0.main_keys)).all()

    # latch kernel, enough unroll: identical decisions + merge
    _, st_ok, out_ok = run(latch=True, unroll=n + 2)
    assert not bool(np.asarray(out_ok.unconverged).any())
    assert (
        np.asarray(out_ok.verdict) == np.asarray(out_exact.verdict)
    ).all()
    assert (
        np.asarray(st_ok.main_ver) == np.asarray(st_exact.main_ver)
    ).all()
