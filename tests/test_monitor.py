"""fdbmonitor analog: conf-driven supervision, restart-on-death, reload.

The supervisor must relaunch a SIGKILLed role (with its data dir, so a
persistent tlog recovers), pick up conf changes on reload, and keep the
cluster usable across the restart (fdbmonitor/fdbmonitor.cpp's contract).
"""

import asyncio
import os
import time

import pytest

from foundationdb_tpu.cluster import multiprocess as mp
from foundationdb_tpu.cluster.monitor import Monitor, parse_conf
from foundationdb_tpu.models.types import CommitTransaction
from foundationdb_tpu.wire.codec import Mutation


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def write_conf(path, socket_dir, tlog_dir, extra=""):
    with open(path, "w") as f:
        f.write(f"""
[role.r0]
kind = resolver
socket_dir = {socket_dir}

[role.t0]
kind = tlog
socket_dir = {socket_dir}
data_dir = {tlog_dir}
{extra}
""")


def test_parse_conf(tmp_path):
    conf = tmp_path / "cluster.conf"
    write_conf(conf, str(tmp_path), str(tmp_path / "td"))
    specs = parse_conf(str(conf))
    assert set(specs) == {"r0", "t0"}
    assert specs["t0"].kind == "tlog"
    assert specs["t0"].data_dir == str(tmp_path / "td")
    assert specs["r0"].data_dir is None


def test_restart_on_death_and_reload(tmp_path):
    conf = tmp_path / "cluster.conf"
    sock_dir = str(tmp_path / "socks")
    os.makedirs(sock_dir)
    tlog_dir = str(tmp_path / "tlog-data")
    write_conf(conf, sock_dir, tlog_dir)
    mon = Monitor(str(conf), log=lambda *a: None)
    mon.start_all()
    try:
        tlog_addr = mon.children["t0"].spec.address

        async def push_one(version, prev):
            c = await mp.connect(tlog_addr)
            try:
                rep = await c.call(
                    mp.TOKEN_TLOG_PUSH,
                    mp.TLogPush(version=version, prev_version=prev,
                                mutations=[Mutation(0, b"k", b"v")]),
                )
                return rep.durable_version
            finally:
                await c.close()

        assert run(push_one(10, -1)) == 10

        # SIGKILL the tlog; the monitor must relaunch it with the same
        # data dir, and the DiskQueue recovery must restore version 10
        pid = mon.children["t0"].proc.proc.pid
        mon.children["t0"].proc.proc.kill()
        mon.children["t0"].proc.proc.wait()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            mon.poll_once()
            if mon.children["t0"].proc.proc.poll() is None and \
                    mon.children["t0"].proc.proc.pid != pid:
                break
            time.sleep(0.1)
        assert mon.restarts.get("t0") == 1

        async def get_version():
            c = await mp.connect(tlog_addr)
            try:
                rep = await c.call(
                    mp.TOKEN_TLOG_VERSION, mp.RoleVersionReq(pad=0))
                return rep.version
            finally:
                await c.close()

        assert run(get_version()) == 10  # recovered from disk
        assert run(push_one(20, 10)) == 20  # and accepting new pushes

        # conf reload: add a storage role, drop the resolver
        with open(conf, "w") as f:
            f.write(f"""
[role.t0]
kind = tlog
socket_dir = {sock_dir}
data_dir = {tlog_dir}

[role.s0]
kind = storage
socket_dir = {sock_dir}
""")
        mon.reload()
        assert set(mon.children) == {"t0", "s0"}

        async def storage_up():
            c = await mp.connect(mon.children["s0"].spec.address)
            try:
                rep = await c.call(
                    mp.TOKEN_STORAGE_VERSION, mp.RoleVersionReq(pad=0))
                return rep.version
            finally:
                await c.close()

        assert run(storage_up()) == 0
    finally:
        mon.stop_all()
