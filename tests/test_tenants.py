"""Tenant isolation and management tests."""

import pytest

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.cluster.tenant import (
    Tenant,
    TenantExists,
    TenantNotEmpty,
    TenantNotFound,
    create_tenant,
    delete_tenant,
    list_tenants,
)


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


@pytest.fixture
def world():
    sched, cluster, db = open_cluster(ClusterConfig())
    yield sched, cluster, db
    cluster.stop()


def test_tenant_isolation(world):
    sched, cluster, db = world

    async def body():
        await create_tenant(db, b"alpha")
        await create_tenant(db, b"beta")
        a, b = Tenant(db, b"alpha"), Tenant(db, b"beta")

        ta = a.create_transaction()
        await ta.set(b"k", b"from-alpha")
        await ta.commit()
        tb = b.create_transaction()
        await tb.set(b"k", b"from-beta")
        await tb.commit()

        ta = a.create_transaction()
        tb = b.create_transaction()
        va = await ta.get(b"k")
        vb = await tb.get(b"k")
        ra = await ta.get_range(b"", b"\xff")
        return va, vb, ra

    va, vb, ra = run(sched, body())
    assert va == b"from-alpha"
    assert vb == b"from-beta"     # same key name, different keyspaces
    assert ra == [(b"k", b"from-alpha")]


def test_tenant_management_errors(world):
    sched, cluster, db = world

    async def body():
        await create_tenant(db, b"t1")
        with pytest.raises(TenantExists):
            await create_tenant(db, b"t1")
        with pytest.raises(TenantNotFound):
            Tenant(db, b"missing")
            t = Tenant(db, b"missing")
            txn = t.create_transaction()
            await txn.get(b"x")
        t1 = Tenant(db, b"t1")
        txn = t1.create_transaction()
        await txn.set(b"data", b"1")
        await txn.commit()
        with pytest.raises(TenantNotEmpty):
            await delete_tenant(db, b"t1")
        txn = t1.create_transaction()
        await txn.clear(b"data")
        await txn.commit()
        await delete_tenant(db, b"t1")
        return await list_tenants(db)

    assert run(sched, body()) == []


def test_tenant_retry_loop_and_conflicts(world):
    sched, cluster, db = world

    async def body():
        await create_tenant(db, b"rt")
        t = Tenant(db, b"rt")

        async def w(txn):
            await txn.atomic_op("add", b"ctr", (1).to_bytes(8, "little"))

        for _ in range(3):
            await t.run(w)
        txn = t.create_transaction()
        return await txn.get(b"ctr")

    assert int.from_bytes(run(sched, body()), "little") == 3
