"""Multi-region skeleton: log-router replication + remote-DC failover.

VERDICT r2 task 8. The remote region trails the primary by a bounded
version lag via the LogRouter's pull stream; failover promotes the
remote with data parity at the takeover version
(fdbserver/LogRouter.actor.cpp + TagPartitionedLogSystem multi-region,
ha-write-path.rst).
"""

from __future__ import annotations

import numpy as np
import pytest

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.cluster.multiregion import RemoteDC


def _run(sched, coro):
    t = sched.spawn(coro)
    sched.run_until(t.done)
    return t.done.get()


@pytest.fixture
def world():
    sched, cluster, db = open_cluster(ClusterConfig(n_storage=2))
    yield sched, cluster, db
    cluster.stop()


def test_remote_dc_replicates_and_fails_over(world):
    sched, cluster, db = world
    remote = RemoteDC(
        sched, cluster.tlog, n_tlogs=2, n_storage=2,
        storage_boundaries=[b"m"],
    )
    remote.start()

    committed: dict[bytes, tuple[int, bytes]] = {}

    async def workload():
        for i in range(25):
            txn = db.create_transaction()
            k = b"mr%02d" % (i % 12)
            v = b"v%d" % i
            txn.set(k, v)
            cid = await txn.commit()
            committed[k] = (txn.committed_version, v)

    _run(sched, workload())
    _run(sched, remote.wait_caught_up())
    assert remote.lag() == 0

    # graceful failover: nothing acked may be lost
    takeover = _run(sched, remote.failover())
    for k, (v_committed, v) in committed.items():
        assert v_committed <= takeover
        got = _run(sched, remote.read_at(k, takeover))
        assert got == v, f"{k!r}: {got!r} != {v!r}"


def test_remote_dc_bounded_lag_during_load(world):
    sched, cluster, db = world
    remote = RemoteDC(sched, cluster.tlog, n_tlogs=1, n_storage=1)
    remote.start()

    async def workload():
        for i in range(30):
            txn = db.create_transaction()
            txn.set(b"lag%02d" % (i % 8), b"x%d" % i)
            await txn.commit()

    _run(sched, workload())
    # the router keeps pulling while load flows; shortly after the last
    # commit the remote must be fully caught up (lag -> 0)
    _run(sched, remote.wait_caught_up())
    assert remote.lag() == 0
    remote.stop()


def test_remote_dc_primary_death_serves_watermark_prefix(world):
    sched, cluster, db = world
    remote = RemoteDC(sched, cluster.tlog, n_tlogs=1, n_storage=2,
                      storage_boundaries=[b"m"])
    remote.start()

    committed: dict[bytes, tuple[int, bytes]] = {}

    async def workload():
        for i in range(20):
            txn = db.create_transaction()
            k = b"pd%02d" % (i % 10)
            v = b"w%d" % i
            txn.set(k, v)
            await txn.commit()
            committed[k] = (txn.committed_version, v)

    _run(sched, workload())
    _run(sched, remote.wait_caught_up())

    # primary dies hard: every log replica gone
    cluster.tlog.live = [False] * len(cluster.tlog.live)

    takeover = _run(sched, remote.failover())
    # the remote serves a consistent prefix at its watermark: everything
    # acked at or below the takeover version is present and correct
    for k, (v_committed, v) in committed.items():
        if v_committed <= takeover:
            got = _run(sched, remote.read_at(k, takeover))
            assert got == v


def test_satellite_logs_rpo_zero_on_primary_dc_death():
    """The VERDICT r3 gap: with satellite logs, kill the WHOLE primary
    DC while the router is behind — every acked commit must survive
    into the promoted remote region (RPO=0, ha-write-path.rst)."""
    sched, cluster, db = open_cluster(
        ClusterConfig(n_storage=2, n_tlogs=2, n_satellite_logs=2)
    )
    try:
        remote = RemoteDC(sched, cluster.tlog, n_tlogs=1, n_storage=2,
                          storage_boundaries=[b"m"])
        remote.start()

        committed: dict[bytes, tuple[int, bytes]] = {}

        async def workload(n0, n1):
            for i in range(n0, n1):
                txn = db.create_transaction()
                k = b"sat%02d" % (i % 10)
                v = b"s%d" % i
                txn.set(k, v)
                await txn.commit()
                committed[k] = (txn.committed_version, v)

        _run(sched, workload(0, 10))
        _run(sched, remote.wait_caught_up())

        # wedge the router (network partition between regions): commits
        # keep flowing and keep acking — satellites hold the stream the
        # remote has NOT seen
        remote.router._task.cancel()
        remote.router._task = None
        _run(sched, workload(10, 25))
        last_acked = max(v for v, _ in committed.values())
        assert remote.logs.version.get() < last_acked  # genuinely behind

        # the disaster: every main log replica dies at once
        cluster.tlog.kill_dc()

        takeover = _run(sched, remote.failover())
        # RPO=0: the takeover covers every acked commit, and each one
        # reads back correctly from the promoted region
        assert takeover >= last_acked, (takeover, last_acked)
        for k, (v_committed, v) in committed.items():
            got = _run(sched, remote.read_at(k, takeover))
            assert got == v, f"{k!r}: {got!r} != {v!r}"
    finally:
        cluster.stop()


def test_satellite_death_does_not_lose_acked_data():
    """One satellite dying leaves the other carrying the stream: the
    failover still recovers everything acked."""
    sched, cluster, db = open_cluster(
        ClusterConfig(n_storage=1, n_tlogs=1, n_satellite_logs=2)
    )
    try:
        remote = RemoteDC(sched, cluster.tlog, n_tlogs=1, n_storage=1)
        remote.start()
        committed = {}

        async def workload(n0, n1):
            for i in range(n0, n1):
                txn = db.create_transaction()
                k = b"sd%02d" % (i % 6)
                v = b"d%d" % i
                txn.set(k, v)
                await txn.commit()
                committed[k] = (txn.committed_version, v)

        _run(sched, workload(0, 8))
        cluster.tlog.kill_satellite(0)
        remote.router._task.cancel()
        remote.router._task = None
        _run(sched, workload(8, 16))

        cluster.tlog.kill_dc()
        takeover = _run(sched, remote.failover())
        assert takeover >= max(v for v, _ in committed.values())
        for k, (_vc, v) in committed.items():
            assert _run(sched, remote.read_at(k, takeover)) == v
    finally:
        cluster.stop()
