"""Mesh-sharded delta-tiered kernel parity (ISSUE 11).

The production tiered path under shard_map (parallel/sharding.py via
TpuConflictSet(config.n_shards > 1)) must reproduce the reference's
multi-resolver deployment bit-for-bit: independent per-shard tiered
histories over a keyspace partition, locally-committed writes merged
per shard (phantom commits included), verdicts min-combined on device
(`pmin`; conflict-read bitmasks via `psum`). Oracles:

* MultiResolverOracle — the reference semantics model (always exact);
* the classic sharded kernel (ShardedConflictSet) — same semantics,
  different machinery (always exact);
* the SINGLE-DEVICE tiered kernel — exact whenever no transaction can
  phantom-commit across shards: a degenerate partition (one empty
  shard) and shard-local workloads pin that equivalence.

Covers the ISSUE-11 satellite checklist: 1/2/4/8 virtual-device CPU
meshes, duplicate/overlapping-range and window-edge streams, per-shard
compaction-cadence invariance, the dedup-latch fallback, per-shard
overflow surviving compaction, and the PR-3 ResolutionBalancer
conservative-writes audit shape with the sharded kernel in the sim.

Runs in the kernel parity lane (8-device CPU mesh, -m kernel).
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
import pytest

from foundationdb_tpu.config import KernelConfig
from foundationdb_tpu.models.conflict_set import (
    CpuConflictSet,
    HistoryOverflowError,
    TpuConflictSet,
)
from foundationdb_tpu.models.types import CommitTransaction
from foundationdb_tpu.parallel.mesh import cpu_mesh
from foundationdb_tpu.parallel.sharding import (
    ShardedConflictSet,
    default_boundaries,
)
from foundationdb_tpu.testing.oracle import MultiResolverOracle, OracleTxn
from foundationdb_tpu.utils import packing
from foundationdb_tpu.utils.packing import stack_device_args

from conftest import random_range

# compile-heavy kernel tests: run with -m kernel (fast lane: -m 'not kernel')
pytestmark = pytest.mark.kernel


def tiered_config(n_shards=0, **kw):
    d = dict(
        max_key_bytes=8,
        max_txns=16,
        max_reads=32,
        max_writes=32,
        history_capacity=512,
        window_versions=1000,
        delta_capacity=256,
        compact_interval=1,
        n_shards=n_shards,
    )
    d.update(kw)
    return KernelConfig(**d)


def make_sharded(cfg, boundaries):
    return TpuConflictSet(
        cfg, mesh=cpu_mesh(cfg.n_shards), shard_boundaries=boundaries
    )


def even_boundaries(n):
    # conftest.random_range draws keys from alphabet bytes 0..3, so the
    # interior splits land inside that space to spread load across
    # shards (default_boundaries' byte-prefix split would put every
    # test key in shard 0 — legal, but it wouldn't exercise clipping).
    # For n=8 the odd splits bisect each first-byte bucket.
    if n <= 4:
        return [bytes([(4 * (i + 1)) // n]) for i in range(n - 1)]
    assert n == 8
    return [
        bytes([i // 2, 2]) if i % 2 else bytes([i // 2])
        for i in range(1, 8)
    ]


def to_oracle(txns):
    return [
        OracleTxn(
            read_conflict_ranges=t.read_conflict_ranges,
            write_conflict_ranges=t.write_conflict_ranges,
            read_snapshot=t.read_snapshot,
            report_conflicting_keys=t.report_conflicting_keys,
        )
        for t in txns
    ]


def random_txn(rng, *, snap_lo, snap_hi, n_ranges=2, blind_prob=0.15,
               dup_pool=None, report_prob=0.5):
    def draw():
        if dup_pool is not None and rng.random() < 0.7:
            return dup_pool[int(rng.integers(0, len(dup_pool)))]
        return random_range(rng)

    reads = [] if rng.random() < blind_prob else [
        draw() for _ in range(1 + int(rng.integers(0, n_ranges)))
    ]
    writes = [draw() for _ in range(1 + int(rng.integers(0, n_ranges)))]
    return CommitTransaction(
        read_conflict_ranges=reads,
        write_conflict_ranges=writes,
        read_snapshot=int(rng.integers(snap_lo, snap_hi)),
        report_conflicting_keys=bool(rng.random() < report_prob),
    )


def gen_stream(rng, n_batches, *, base=1000, step=100, n_txns=10,
               dup_pool=None):
    out = []
    for i in range(n_batches):
        version = base + (i + 1) * step
        out.append((
            [
                random_txn(
                    rng, snap_lo=max(0, base - 2 * step), snap_hi=version,
                    dup_pool=dup_pool,
                )
                for _ in range(n_txns)
            ],
            version,
        ))
    return out


def run_verdicts(cs, stream):
    return [
        [int(v) for v in cs.resolve(txns, ver).verdicts]
        for txns, ver in stream
    ]


def oracle_verdicts(oracle, stream):
    return [
        oracle.resolve(to_oracle(txns), ver).verdicts
        for txns, ver in stream
    ]


# ---------------------------------------------------------------------------
# Random-stream parity at every mesh width.


@pytest.mark.parametrize("n_shards", [2, 4, 8])
def test_sharded_tiered_matches_multi_resolver_oracle(n_shards):
    rng = np.random.default_rng(n_shards)
    boundaries = even_boundaries(n_shards)
    cfg = tiered_config(n_shards=n_shards)
    dev = make_sharded(cfg, boundaries)
    oracle = MultiResolverOracle(boundaries, window=cfg.window_versions)
    stream = gen_stream(rng, 6)
    assert run_verdicts(dev, stream) == oracle_verdicts(oracle, stream)


def test_sharded_columnar_matches_multi_resolver_oracle():
    """The r12 acceptance pin: the COLUMNAR wire frame driven through a
    2-shard mesh (proxy-side pack_columnar -> codec roundtrip ->
    resolve_columnar, exactly the wire ResolverRole's path) must match
    the multi-resolver oracle AND the object-path sharded instance
    batch for batch — pack once, shard the same arrays over the mesh.
    """
    from foundationdb_tpu.wire import codec

    rng = np.random.default_rng(12)
    boundaries = even_boundaries(2)
    cfg = tiered_config(n_shards=2)
    dev_obj = make_sharded(cfg, boundaries)
    dev_col = make_sharded(cfg, boundaries)
    oracle = MultiResolverOracle(boundaries, window=cfg.window_versions)
    stream = gen_stream(rng, 6)
    got_obj = run_verdicts(dev_obj, stream)
    got_col = []
    for txns, ver in stream:
        msg = codec.decode(codec.encode(codec.ResolveBatchColumnar(
            prev_version=-1, version=ver, last_received_version=-1,
            cols=packing.pack_columnar(txns),
        )))
        res = dev_col.resolve_columnar(msg.cols, ver)
        got_col.append([int(v) for v in res.verdicts])
    assert got_col == got_obj == oracle_verdicts(oracle, stream)


@pytest.mark.parametrize("n_shards", [2, 4])
def test_sharded_tiered_matches_classic_sharded(n_shards):
    """Same reference multi-resolver semantics, different machinery:
    the tiered shard_map kernel vs the classic single-tier shard_map
    kernel must agree batch for batch."""
    rng = np.random.default_rng(40 + n_shards)
    boundaries = even_boundaries(n_shards)
    cfg = tiered_config(n_shards=n_shards)
    dev = make_sharded(cfg, boundaries)
    classic = ShardedConflictSet(
        dataclasses.replace(cfg, n_shards=0, delta_capacity=0),
        cpu_mesh(n_shards), boundaries,
    )
    stream = gen_stream(rng, 6)
    for txns, ver in stream:
        got = [int(v) for v in dev.resolve(txns, ver).verdicts]
        want = np.asarray(
            classic.resolve(txns, ver).verdict
        )[: len(txns)].tolist()
        assert got == want


# ---------------------------------------------------------------------------
# Single-device equivalence on phantom-free shapes.


def test_degenerate_partition_matches_single_device():
    """A partition whose interior boundary exceeds every live key keeps
    ALL activity on shard 0 — no transaction can phantom-commit across
    shards, so the 2-shard mesh must equal the single-device tiered
    kernel exactly (verdicts AND conflicting-key reports)."""
    rng = np.random.default_rng(5)
    cfg = tiered_config(n_shards=2)
    dev = make_sharded(cfg, [b"\xf0\xf0\xf0"])
    single = TpuConflictSet(dataclasses.replace(cfg, n_shards=0))
    stream = gen_stream(rng, 6)
    for txns, ver in stream:
        got = dev.resolve(txns, ver)
        want = single.resolve(txns, ver)
        assert got.verdicts == want.verdicts
        assert got.conflicting_key_ranges == want.conflicting_key_ranges


def test_shard_local_workload_matches_single_device():
    """Each transaction's ranges confined to ONE shard: clipping routes
    every whole transaction to exactly one shard, phantom commits are
    impossible, and the 4-shard decisions equal the single-device
    kernel's."""
    rng = np.random.default_rng(9)
    boundaries = even_boundaries(4)
    cfg = tiered_config(n_shards=4)
    dev = make_sharded(cfg, boundaries)
    single = TpuConflictSet(dataclasses.replace(cfg, n_shards=0))

    def local_txn(version):
        first = int(rng.integers(0, 4))  # the owning shard's byte
        def key():
            return bytes([first]) + bytes(
                rng.integers(0, 4, size=int(rng.integers(1, 4)),
                             dtype=np.uint8)
            )
        def rr():
            a, b = sorted([key(), key()])
            return (a, b) if a != b else (a, a + b"\x00")
        return CommitTransaction(
            read_conflict_ranges=[rr() for _ in range(2)],
            write_conflict_ranges=[rr()],
            read_snapshot=int(rng.integers(800, version)),
        )

    version = 1000
    for _ in range(8):
        version += 100
        txns = [local_txn(version) for _ in range(10)]
        got = dev.resolve(txns, version)
        want = single.resolve(txns, version)
        assert got.verdicts == want.verdicts


# ---------------------------------------------------------------------------
# Adversarial shapes: duplicates/overlaps, window edges, cadences.


@pytest.mark.parametrize("seed", range(2))
def test_duplicate_and_overlapping_ranges_dedup_parity(seed):
    """Hot-key adversarial stream (most ranges from a small duplicate
    pool): the PER-SHARD dedup probe must be decision-identical to
    dedup-off and to the multi-resolver oracle."""
    rng = np.random.default_rng(200 + seed)
    pool = [random_range(rng) for _ in range(4)]
    stream = gen_stream(rng, 5, dup_pool=pool)
    boundaries = even_boundaries(2)
    oracle = MultiResolverOracle(boundaries, window=1000)
    want = oracle_verdicts(oracle, stream)
    res_d = run_verdicts(
        make_sharded(tiered_config(n_shards=2, dedup_reads=16), boundaries),
        stream,
    )
    res_p = run_verdicts(
        make_sharded(tiered_config(n_shards=2), boundaries), stream
    )
    assert res_d == want
    assert res_p == want


def test_window_edge_versions_sharded():
    """Snapshots exactly at / one beside the MVCC floor, with the two
    ranges on DIFFERENT shards: the too-old boundary and GC floor must
    match the multi-resolver oracle at every offset."""
    boundaries = [b"\x02"]
    cfg = tiered_config(n_shards=2, window_versions=100)
    dev = make_sharded(cfg, boundaries)
    oracle = MultiResolverOracle(boundaries, window=100)
    k = lambda i: bytes([i])
    stream = []
    for snap in (99, 100, 101, 199, 200):
        stream.append((
            [
                CommitTransaction([(k(1), k(2))], [(k(1), k(2))],
                                  read_snapshot=snap),
                CommitTransaction([(k(3), k(4))], [(k(3), k(4))],
                                  read_snapshot=snap),
                CommitTransaction([], [(k(1), k(4))], read_snapshot=snap),
            ],
            200 + len(stream),
        ))
    assert run_verdicts(dev, stream) == oracle_verdicts(oracle, stream)


def canonical_map_rows(main_keys, main_ver):
    rows = []
    for j in range(main_keys.shape[0]):
        if all(x == 0xFFFFFFFF for x in main_keys[j]):
            continue
        rows.append((tuple(main_keys[j]), int(main_ver[j])))
    rows.sort()
    dedup = {}
    for kk, v in rows:
        dedup[kk] = v
    out = []
    for kk in sorted(dedup):
        if not out or out[-1][1] != dedup[kk]:
            out.append((kk, dedup[kk]))
    return out


@pytest.mark.parametrize("interval", [2, 4, 0])
def test_compaction_cadence_invariance_per_shard(interval):
    """Decisions must not depend on WHEN each shard folds delta into
    main, and after a final explicit compaction every shard's combined
    key->version map must be identical across cadences."""
    rng = np.random.default_rng(42)
    stream = gen_stream(rng, 6)
    boundaries = even_boundaries(2)
    ref_cfg = tiered_config(n_shards=2, compact_interval=1,
                            delta_capacity=512)
    ref = make_sharded(ref_cfg, boundaries)
    want = run_verdicts(ref, stream)
    ref.compact_history()
    ref_maps = [
        canonical_map_rows(
            np.asarray(ref.state.main.main_keys)[s],
            np.asarray(ref.state.main.main_ver)[s],
        )
        for s in range(2)
    ]
    cs = make_sharded(
        tiered_config(n_shards=2, compact_interval=interval,
                      delta_capacity=512),
        boundaries,
    )
    assert run_verdicts(cs, stream) == want, f"interval={interval}"
    cs.compact_history()
    from foundationdb_tpu.ops import delta as D

    _, d_cnt = D.boundary_counts_per_shard(cs.state)
    assert np.asarray(d_cnt).tolist() == [0, 0]
    got_maps = [
        canonical_map_rows(
            np.asarray(cs.state.main.main_keys)[s],
            np.asarray(cs.state.main.main_ver)[s],
        )
        for s in range(2)
    ]
    assert got_maps == ref_maps, (
        f"interval={interval}: per-shard post-compaction maps diverge"
    )


# ---------------------------------------------------------------------------
# Latch / overflow disciplines.


def test_dedup_latch_trips_all_shards_unchanged_and_fallback():
    """More distinct live read ranges than dedup_reads on SOME shard:
    the raw kernel must refuse the whole group (unconverged reduced
    across shards) with EVERY shard's tiers unchanged; the checked host
    path must auto-redispatch the exact kernel and serve decisions
    identical to dedup-off."""
    rng = np.random.default_rng(3)
    boundaries = even_boundaries(2)
    cfg = tiered_config(n_shards=2, dedup_reads=2, compact_interval=0)
    stream = gen_stream(rng, 3)
    batches = [packing.pack_batch(t, v, 0, cfg) for t, v in stream]
    stacked = stack_device_args(batches)

    cs_raw = make_sharded(cfg, boundaries)
    before = jax.tree.map(lambda x: np.asarray(x).copy(), cs_raw.state)
    outs_raw = cs_raw.resolve_group_args(stacked, check_latch=False)
    assert bool(np.asarray(outs_raw.unconverged).all())
    for a, b in zip(
        jax.tree_util.tree_leaves(before),
        jax.tree_util.tree_leaves(cs_raw.state),
    ):
        np.testing.assert_array_equal(a, np.asarray(b))

    cs = make_sharded(cfg, boundaries)
    outs = cs.resolve_group_args(stacked)
    assert not bool(np.asarray(outs.unconverged).any())
    assert cs.metrics.counters.get("exactFallbacks") >= 1
    ref = make_sharded(
        tiered_config(n_shards=2, compact_interval=0), boundaries
    ).resolve_group_args(stacked)
    np.testing.assert_array_equal(
        np.asarray(outs.verdict), np.asarray(ref.verdict)
    )


def test_per_shard_overflow_survives_compaction():
    """Writes aimed at ONE shard overflow only that shard's delta; the
    latched overflow must fold into that shard's main tier across a
    compaction so check_overflow still raises — per-shard overflow is
    never silently lost in the collective accounting."""
    boundaries = [b"\x02"]
    cfg = tiered_config(n_shards=2, delta_capacity=4, compact_interval=0)
    k = lambda i: bytes([i])
    txns = [
        CommitTransaction([], [(k(4 + 2 * i), k(5 + 2 * i))],
                          read_snapshot=50)
        for i in range(8)
    ]  # 16 distinct boundaries, all >= \x02 -> shard 1 only
    cs = make_sharded(cfg, boundaries)
    batch = packing.pack_batch(txns, 100, 0, cfg)
    cs.resolve_group_args(stack_device_args([batch]), check_latch=False)
    ov = np.asarray(cs.state.delta.overflow)
    assert ov.tolist() == [False, True]
    cs.compact_history()
    assert not np.asarray(cs.state.delta.overflow).any()
    with pytest.raises(HistoryOverflowError):
        cs.check_overflow()


def test_sharded_overflow_raises_loudly():
    boundaries = [b"\x02"]
    cfg = tiered_config(n_shards=2, delta_capacity=4, compact_interval=0)
    k = lambda i: bytes([i])
    txns = [
        CommitTransaction([], [(k(4 + 2 * i), k(5 + 2 * i))],
                          read_snapshot=50)
        for i in range(8)
    ]
    cs = make_sharded(cfg, boundaries)
    with pytest.raises(HistoryOverflowError):
        cs.resolve(txns, 100)


# ---------------------------------------------------------------------------
# Group / pipelined dispatch paths + rebase.


def test_sharded_group_path_matches_per_batch():
    """resolve_group_args (one shard_map program for the whole stack)
    must equal the per-batch sharded path batch for batch."""
    rng = np.random.default_rng(7)
    boundaries = even_boundaries(2)
    cfg = tiered_config(n_shards=2, compact_interval=2)
    stream = gen_stream(rng, 6, n_txns=8)
    batches = [packing.pack_batch(t, v, 0, cfg) for t, v in stream]

    seq = make_sharded(cfg, boundaries)
    seq_out = [seq.resolve_args(b.device_args()) for b in batches]

    grp = make_sharded(cfg, boundaries)
    outs = [
        grp.resolve_group_args(stack_device_args(batches[lo:lo + 3]))
        for lo in (0, 3)
    ]
    for i in range(6):
        g, kk = divmod(i, 3)
        np.testing.assert_array_equal(
            np.asarray(outs[g].verdict[kk]), np.asarray(seq_out[i].verdict),
            err_msg=f"verdict batch {i}",
        )
        np.testing.assert_array_equal(
            np.asarray(outs[g].hist_conflict_read[kk]),
            np.asarray(seq_out[i].hist_conflict_read),
            err_msg=f"hist_conflict_read batch {i}",
        )


def test_sharded_pipelined_stream_matches_per_batch():
    """resolve_stream_pipelined on a sharded instance: the staging
    thread's mesh-replicated device_puts must feed the same decisions
    as the per-batch path, chunk by chunk."""
    rng = np.random.default_rng(11)
    boundaries = even_boundaries(2)
    cfg = tiered_config(n_shards=2, compact_interval=2)
    stream = gen_stream(rng, 6, n_txns=8)
    batches = [packing.pack_batch(t, v, 0, cfg) for t, v in stream]
    seq = make_sharded(cfg, boundaries)
    seq_out = [seq.resolve_args(b.device_args()) for b in batches]

    cs = make_sharded(cfg, boundaries)
    outs = cs.resolve_stream_pipelined(batches, chunk=3)
    flat = [
        (g, kk)
        for g in range(len(outs))
        for kk in range(np.asarray(outs[g].verdict).shape[0])
    ]
    assert len(flat) == len(batches)
    for i, (g, kk) in enumerate(flat):
        np.testing.assert_array_equal(
            np.asarray(outs[g].verdict[kk]), np.asarray(seq_out[i].verdict),
            err_msg=f"pipelined batch {i}",
        )
    assert cs.metrics.counters.get("stagedChunks") == 2


def test_sharded_rebase_matches_oracle():
    """The int32 offset rebase must shift every shard's tiers (a
    cross-shard phantom surviving a rebase still conflicts right)."""
    from foundationdb_tpu.models.conflict_set import REBASE_THRESHOLD

    boundaries = [b"\x08"]
    cfg = tiered_config(n_shards=2, window_versions=1 << 33,
                        compact_interval=0)
    k = lambda i: bytes([i])
    v0 = 1000
    far = v0 + REBASE_THRESHOLD + (1 << 21)
    stream = [
        ([CommitTransaction([], [(k(5), k(6))], read_snapshot=v0 - 1),
          CommitTransaction([], [(k(9), k(10))], read_snapshot=v0 - 1)],
         v0),
        ([CommitTransaction([(k(5), k(6))], [(k(9), k(10))],
                            read_snapshot=v0 - 1),
          CommitTransaction([(k(9), k(10))], [(k(11), k(12))],
                            read_snapshot=far - 1)],
         far),
    ]
    dev = make_sharded(cfg, boundaries)
    oracle = MultiResolverOracle(boundaries, window=cfg.window_versions)
    got = run_verdicts(dev, stream)
    assert got == oracle_verdicts(oracle, stream)
    assert dev.metrics.counters.get("rebases") == 1


# ---------------------------------------------------------------------------
# Structural pins: one program per group, no recompile churn.


def test_one_compiled_program_per_group():
    """The sharded dispatch is ONE shard_map program per group: after
    the first (compiling) dispatch, further same-shape groups add zero
    backend compiles and exactly one groupDispatch each — the
    no-host-round-trip pin behind the compile-count ledger metric."""
    from foundationdb_tpu.utils import compile_cache

    compile_cache.instrument()
    rng = np.random.default_rng(13)
    boundaries = even_boundaries(2)
    cfg = tiered_config(n_shards=2, compact_interval=0)
    streams = [gen_stream(rng, 3, base=1000 + 600 * i) for i in range(3)]
    stacks = [
        stack_device_args(
            [packing.pack_batch(t, v, 0, cfg) for t, v in st]
        )
        for st in streams
    ]
    cs = make_sharded(cfg, boundaries)
    cs.resolve_group_args(stacks[0])  # warm (may compile)
    before = compile_cache.stats()["backend_compiles"]
    d0 = cs.metrics.counters.get("groupDispatches")
    for st in stacks[1:]:
        cs.resolve_group_args(st)
    assert compile_cache.stats()["backend_compiles"] == before
    assert cs.metrics.counters.get("groupDispatches") == d0 + 2


def test_sharded_metrics_surface():
    """The fdbtop kernel-panel keys: shard count, worst-shard tier
    occupancy and the measured collective share must flow through
    KernelStageMetrics.qos() on a sharded instance (and exist, zeroed,
    on single-device ones — the REQUIRED_SENSORS contract)."""
    rng = np.random.default_rng(17)
    boundaries = even_boundaries(2)
    cfg = tiered_config(n_shards=2)
    cs = make_sharded(cfg, boundaries)
    for txns, ver in gen_stream(rng, 3):
        cs.resolve(txns, ver)
    cs.check_overflow()
    q = cs.metrics.qos()
    assert q["shards"] == 2
    assert q["worst_shard_main_occupancy"] > 0
    assert 0.0 < q["collective_time_share"] <= 1.0
    single = TpuConflictSet(dataclasses.replace(cfg, n_shards=0))
    q1 = single.metrics.qos()
    assert q1["shards"] == 1
    assert q1["collective_time_share"] == 0.0


def test_config_validation():
    with pytest.raises(ValueError, match="tiered-only"):
        KernelConfig(delta_capacity=0, n_shards=2)
    with pytest.raises(ValueError, match="n_shards"):
        KernelConfig(delta_capacity=64, n_shards=-1)
    # mesh/boundary mismatches are loud
    cfg = tiered_config(n_shards=2)
    with pytest.raises(ValueError, match="interior"):
        TpuConflictSet(cfg, mesh=cpu_mesh(2), shard_boundaries=[])
    assert len(default_boundaries(4)) == 3


# ---------------------------------------------------------------------------
# The PR-3 ResolutionBalancer conservative-writes audit shape, with the
# sharded kernel inside the sim ensemble.


def test_sharded_soak_seed_passes_with_balancer_audit_shape():
    """api_correctness seed 8: tpu-force and seed % 4 == 0, so the sim
    Resolver runs the MESH-SHARDED tiered kernel inside the fault
    ensemble. The seed must pass every gate — in particular the PR-3
    strict false-abort audit arming rule (single-resolver fault-free
    plans only) must keep tolerating the sharded kernel's
    reference-semantics phantom commits exactly as it tolerates the
    ResolutionBalancer's conservative writes."""
    from foundationdb_tpu.testing.soak import (
        _sharded_mesh_available,
        plan_for_seed,
        run_seed,
    )

    plan = plan_for_seed(8, "api_correctness")
    assert plan.resolver_backend == "tpu-force"  # the sharded-eligible shape
    assert _sharded_mesh_available(2)  # conftest pinned 8 CPU devices
    sig = run_seed(8, spec="api_correctness")
    assert sig[1] > 0  # commits flowed through the sharded kernel
