"""Tests for the deterministic actor runtime (runtime/flow.py).

Mirrors the contracts the reference's flow primitives guarantee:
single-assignment futures, prioritized deterministic ordering, virtual
time, NotifiedVersion threshold wakeups, actor cancellation.
"""

import pytest

from foundationdb_tpu.runtime.flow import (
    ActorCancelled,
    Notified,
    Promise,
    PromiseStream,
    Scheduler,
    TaskPriority,
    Trigger,
    all_of,
    any_of,
)


def test_promise_future_roundtrip():
    sched = Scheduler(sim=True)
    p = Promise()

    async def consumer():
        return await p.future

    task = sched.spawn(consumer())
    sched._schedule(0.0, TaskPriority.Zero, lambda: p.send(42))
    assert sched.run_until(task.done) == 42


def test_delay_advances_virtual_clock():
    sched = Scheduler(sim=True)

    async def actor():
        await sched.delay(5.0)
        return sched.now()

    t = sched.spawn(actor())
    assert sched.run_until(t.done) == pytest.approx(5.0)


def test_deterministic_ordering_two_runs():
    def run():
        sched = Scheduler(sim=True)
        log = []

        async def worker(name, period):
            for _ in range(5):
                await sched.delay(period)
                log.append((name, sched.now()))

        tasks = [sched.spawn(worker("a", 1.0)), sched.spawn(worker("b", 0.7))]
        sched.run_until(all_of([t.done for t in tasks]))
        return log

    assert run() == run()


def test_priority_ordering_same_time():
    sched = Scheduler(sim=True)
    log = []
    sched._schedule(0.0, TaskPriority.Low, lambda: log.append("low"))
    sched._schedule(0.0, TaskPriority.Max, lambda: log.append("max"))
    sched._schedule(0.0, TaskPriority.DefaultEndpoint, lambda: log.append("mid"))
    done = sched.delay(1.0)
    sched.run_until(done)
    assert log == ["max", "mid", "low"]


def test_notified_when_at_least():
    sched = Scheduler(sim=True)
    n = Notified(0)
    hits = []

    async def waiter(threshold):
        await n.when_at_least(threshold)
        hits.append(threshold)

    tasks = [sched.spawn(waiter(v)) for v in (3, 1, 2)]
    sched.run_for(0.01)  # let the actors reach their await
    assert n.num_waiting() == 3
    n.set(2)
    sched.run_until(all_of([tasks[1].done, tasks[2].done]))
    assert sorted(hits) == [1, 2]
    assert n.num_waiting() == 1
    n.set(3)
    sched.run_until(tasks[0].done)
    assert sorted(hits) == [1, 2, 3]
    with pytest.raises(ValueError):
        n.set(1)


def test_promise_stream_fifo():
    sched = Scheduler(sim=True)
    ps = PromiseStream()
    got = []

    async def consumer():
        for _ in range(3):
            got.append(await ps.stream.next())

    t = sched.spawn(consumer())
    for v in (1, 2, 3):
        ps.send(v)
    sched.run_until(t.done)
    assert got == [1, 2, 3]


def test_actor_cancellation():
    sched = Scheduler(sim=True)
    progress = []

    async def actor():
        progress.append("start")
        await sched.delay(100.0)
        progress.append("never")

    t = sched.spawn(actor())
    sched.run_for(1.0)
    t.cancel()
    sched.run_for(1.0)
    assert progress == ["start"]
    assert t.done.is_error
    with pytest.raises(ActorCancelled):
        t.done.get()


def test_any_of_choose():
    sched = Scheduler(sim=True)

    async def actor():
        idx, _val = await any_of([sched.delay(5.0), sched.delay(2.0)])
        return idx

    t = sched.spawn(actor())
    assert sched.run_until(t.done) == 1


def test_trigger_wakes_all():
    sched = Scheduler(sim=True)
    trig = Trigger()
    woke = []

    async def waiter(i):
        await trig.on_trigger()
        woke.append(i)

    tasks = [sched.spawn(waiter(i)) for i in range(3)]
    sched.run_for(0.1)
    trig.trigger()
    sched.run_until(all_of([t.done for t in tasks]))
    assert sorted(woke) == [0, 1, 2]


def test_actor_error_propagates():
    sched = Scheduler(sim=True)

    async def actor():
        raise RuntimeError("boom")

    t = sched.spawn(actor())
    sched.run_for(0.1)
    with pytest.raises(RuntimeError, match="boom"):
        t.done.get()


def test_deadlock_detection():
    sched = Scheduler(sim=True)
    p = Promise()

    async def actor():
        await p.future

    t = sched.spawn(actor())
    with pytest.raises(RuntimeError, match="deadlock"):
        sched.run_until(t.done)
