"""Long-key conservative degradation: conflicts may be added, never lost.

Conflict-range keys beyond max_key_bytes truncate with round-up on end
keys (packing.pack_key), so the packed ranges are supersets of the real
ones — the kernel must still catch every true conflict (safety), and for
keys within the width it stays exact.
"""

import pytest
import numpy as np

from foundationdb_tpu.config import TEST_CONFIG
from foundationdb_tpu.models.conflict_set import TpuConflictSet
from foundationdb_tpu.models.types import CommitTransaction, TransactionResult
from foundationdb_tpu.testing.oracle import ConflictOracle, OracleTxn

# compile-heavy kernel tests: run with -m kernel (fast lane: -m 'not kernel')
pytestmark = pytest.mark.kernel

CFG = TEST_CONFIG  # max_key_bytes = 8


def test_long_key_true_conflicts_never_missed():
    rng = np.random.default_rng(0)
    cs = TpuConflictSet(CFG)
    oracle = ConflictOracle(window=CFG.window_versions)
    version = 0
    for step in range(10):
        version += 10
        txns = []
        for _ in range(12):
            # keys share an 8-byte prefix and differ beyond the packed
            # width — the worst case for truncation
            prefix = bytes([rng.integers(0, 3)]) * 8
            tail = bytes(rng.integers(0, 3, size=4).tolist())
            k = prefix + tail
            if rng.random() < 0.5:
                txns.append(
                    CommitTransaction(
                        read_conflict_ranges=[(k, k + b"\x01")],
                        read_snapshot=version - int(rng.integers(1, 15)),
                    )
                )
            else:
                txns.append(
                    CommitTransaction(write_conflict_ranges=[(k, k + b"\x01")])
                )
        got = cs.resolve(txns, version)
        want = oracle.resolve(
            [
                OracleTxn(t.read_conflict_ranges, t.write_conflict_ranges,
                          t.read_snapshot)
                for t in txns
            ],
            version,
        )
        for t in range(len(txns)):
            if want.verdicts[t] == 0:  # oracle CONFLICT
                assert got.verdicts[t] == TransactionResult.CONFLICT, (
                    f"step {step} txn {t}: kernel missed a true conflict"
                )
            # the kernel may conservatively conflict where the oracle
            # committed (prefix collision) — that is the allowed direction


def test_short_keys_remain_exact():
    cs = TpuConflictSet(CFG)
    oracle = ConflictOracle(window=CFG.window_versions)
    txns = [
        CommitTransaction(write_conflict_ranges=[(b"a", b"b")]),
        CommitTransaction(
            read_conflict_ranges=[(b"a", b"b")], read_snapshot=0
        ),
    ]
    got = cs.resolve(txns, 10)
    want = oracle.resolve(
        [OracleTxn(t.read_conflict_ranges, t.write_conflict_ranges,
                   t.read_snapshot) for t in txns], 10
    )
    assert [int(v) for v in got.verdicts] == want.verdicts


def test_cluster_handles_long_keys_end_to_end():
    from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster

    sched, cluster, db = open_cluster(ClusterConfig())
    long_key = b"some/very/long/key/path/beyond/width" * 3

    async def body():
        txn = db.create_transaction()
        txn.set(long_key, b"stored-in-full")
        await txn.commit()
        txn = db.create_transaction()
        return await txn.get(long_key)

    # storage keeps full keys; only conflict ranges truncate
    assert sched.run_until(sched.spawn(body()).done) == b"stored-in-full"
    cluster.stop()
