"""Slow-task detection + actor profile (VERDICT r4 missing #9;
flow/Net2.actor.cpp:1462 checkForSlowTask, fdbclient/
ActorLineageProfiler.cpp).

The single-threaded run loop serves nothing while one actor step runs,
so a step exceeding SLOW_TASK_THRESHOLD wall time is a live-lock hazard:
it must surface as a SlowTask trace event and in the scheduler's
per-actor profile — visibility into a stuck/slow actor that the build
previously lacked."""

import time

from foundationdb_tpu.runtime.flow import Scheduler
from foundationdb_tpu.utils import trace


def test_slow_step_surfaces():
    sched = Scheduler(sim=True, profile=True)
    before = len(trace.g_trace.find("SlowTask"))

    async def blocker():
        time.sleep(0.06)  # a step that BLOCKS the loop (wall time)
        return True

    async def quick():
        for _ in range(5):
            await sched.delay(0.01)
        return True

    t1 = sched.spawn(blocker(), name="blocking-actor")
    t2 = sched.spawn(quick(), name="quick-actor")
    sched.run_until(t1.done)
    sched.run_until(t2.done)

    events = trace.g_trace.find("SlowTask")[before:]
    assert any(e["Actor"] == "blocking-actor" for e in events), events
    assert all(e["Ms"] >= 50 for e in events)
    # the profile records both actors; the blocker's max step dominates
    # (positive assertions only: wall-time measurement on a loaded CI
    # host can make ANY step slow, so never assert absence)
    assert sched.actor_profile["blocking-actor"][2] >= 0.05
    assert sched.actor_profile["quick-actor"][0] >= 5  # steps counted
    assert any(name == "blocking-actor" for name, _ in sched.slow_tasks)
