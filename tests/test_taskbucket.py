"""TaskBucket: persistent in-keyspace task queue with leases
(VERDICT r4 missing #7; fdbclient/TaskBucket.actor.cpp)."""

from __future__ import annotations

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.layers.taskbucket import TaskBucket


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


def test_add_claim_finish_roundtrip():
    sched, cluster, db = open_cluster(ClusterConfig())
    tb = TaskBucket(db)

    async def body():
        await tb.add(b"t1", {"op": "copy", "src": "a"})
        await tb.add(b"t2", {"op": "copy", "src": "b"})
        t = await tb.get_one()
        assert t.key == b"t1" and t.params == {"op": "copy", "src": "a"}
        # claimed: not visible to another claimer
        t2 = await tb.get_one()
        assert t2.key == b"t2"
        assert await tb.get_one() is None
        await tb.finish(t)
        await tb.finish(t2)
        assert await tb.is_empty()
        return True

    assert run(sched, body())
    cluster.stop()


def test_crashed_executor_lease_expires_and_requeues():
    sched, cluster, db = open_cluster(ClusterConfig())
    tb = TaskBucket(db)

    async def body():
        await tb.add(b"job", {"n": "1"})
        t = await tb.get_one()
        assert t is not None
        # the executor "crashes": never extends, never finishes
        assert await tb.get_one() is None  # leased: invisible
        await sched.delay(TaskBucket.LEASE + 0.1)
        moved = await tb.check_timeouts()
        assert moved == 1
        t2 = await tb.get_one()  # another executor picks it up
        assert t2 is not None and t2.key == b"job" and t2.params == {"n": "1"}
        await tb.finish(t2)
        assert await tb.is_empty()
        return True

    assert run(sched, body())
    cluster.stop()


def test_extend_keeps_lease_alive():
    sched, cluster, db = open_cluster(ClusterConfig())
    tb = TaskBucket(db)

    async def body():
        await tb.add(b"long", {})
        t = await tb.get_one()
        for _ in range(3):
            await sched.delay(TaskBucket.LEASE * 0.6)
            await tb.extend(t)
        assert await tb.check_timeouts() == 0  # never expired
        await tb.finish(t)
        assert await tb.is_empty()
        return True

    assert run(sched, body())
    cluster.stop()


def test_dependency_unblocks_on_finish():
    sched, cluster, db = open_cluster(ClusterConfig())
    tb = TaskBucket(db)

    async def body():
        await tb.add(b"parent", {"step": "1"})
        await tb.add(b"child", {"step": "2"}, after=b"parent")
        p = await tb.get_one()
        assert p.key == b"parent"
        assert await tb.get_one() is None  # child parked
        await tb.finish(p)
        c = await tb.get_one()
        assert c is not None and c.key == b"child"
        await tb.finish(c)
        assert await tb.is_empty()
        return True

    assert run(sched, body())
    cluster.stop()


def test_concurrent_claimers_get_distinct_tasks():
    sched, cluster, db = open_cluster(ClusterConfig())
    tb = TaskBucket(db)

    async def body():
        for i in range(4):
            await tb.add(b"w%d" % i, {"i": str(i)})

        async def worker():
            got = []
            while True:
                t = await tb.get_one()
                if t is None:
                    return got
                got.append(t.key)
                await tb.finish(t)

        t1 = sched.spawn(worker())
        t2 = sched.spawn(worker())
        g1 = await t1.done
        g2 = await t2.done
        assert sorted(g1 + g2) == [b"w0", b"w1", b"w2", b"w3"]
        assert not (set(g1) & set(g2)), (g1, g2)  # exactly-once
        return True

    assert run(sched, body())
    cluster.stop()


def test_after_already_finished_parent_enqueues_immediately():
    sched, cluster, db = open_cluster(ClusterConfig())
    tb = TaskBucket(db)

    async def body():
        await tb.add(b"p", {})
        t = await tb.get_one()
        await tb.finish(t)
        # parent gone: the dependent must NOT park forever
        await tb.add(b"c", {}, after=b"p")
        c = await tb.get_one()
        assert c is not None and c.key == b"c"
        await tb.finish(c)
        assert await tb.is_empty()
        return True

    assert run(sched, body())
    cluster.stop()


def test_stale_finish_raises_after_requeue():
    """An executor that lost its lease must not mark the task done or
    release dependents under the new owner's feet."""
    sched, cluster, db = open_cluster(ClusterConfig())
    tb = TaskBucket(db)

    async def body():
        await tb.add(b"t", {})
        await tb.add(b"dep", {}, after=b"t")
        a = await tb.get_one()
        await sched.delay(TaskBucket.LEASE + 0.1)
        assert await tb.check_timeouts() == 1
        b = await tb.get_one()
        assert b is not None and b.key == b"t"
        try:
            await tb.finish(a)  # stale: lease was lost
            raise AssertionError("stale finish must raise")
        except KeyError:
            pass
        # dep is still parked (the stale finish released nothing)
        assert (await tb.get_one()) is None
        await tb.finish(b)
        c = await tb.get_one()
        assert c is not None and c.key == b"dep"
        await tb.finish(c)
        return True

    assert run(sched, body())
    cluster.stop()


def test_slashed_parent_keys_unambiguous():
    sched, cluster, db = open_cluster(ClusterConfig())
    tb = TaskBucket(db)

    async def body():
        await tb.add(b"a", {})
        await tb.add(b"a/b", {})
        await tb.add(b"x", {}, after=b"a/b")  # parked on a/b, NOT on a
        pa = await tb.get_one()
        pab = await tb.get_one()
        by_key = {t.key: t for t in (pa, pab)}
        await tb.finish(by_key[b"a"])
        assert (await tb.get_one()) is None  # x still parked
        await tb.finish(by_key[b"a/b"])
        x = await tb.get_one()
        assert x is not None and x.key == b"x"  # key NOT corrupted
        await tb.finish(x)
        assert await tb.is_empty()
        return True

    assert run(sched, body())
    cluster.stop()


def test_blocked_parent_counts_as_live():
    """A parked (blocked) parent is still pending: a grandchild chained
    on it must park, not run early (r5 code review)."""
    sched, cluster, db = open_cluster(ClusterConfig())
    tb = TaskBucket(db)

    async def body():
        await tb.add(b"A", {})
        await tb.add(b"B", {}, after=b"A")   # parked
        await tb.add(b"C", {}, after=b"B")   # B live (parked) -> C parks
        a = await tb.get_one()
        assert a.key == b"A"
        assert (await tb.get_one()) is None  # B and C both parked
        await tb.finish(a)
        b = await tb.get_one()
        assert b.key == b"B"
        assert (await tb.get_one()) is None  # C still waits on B
        await tb.finish(b)
        c = await tb.get_one()
        assert c.key == b"C"
        await tb.finish(c)
        assert await tb.is_empty()
        return True

    assert run(sched, body())
    cluster.stop()
