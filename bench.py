#!/usr/bin/env python
"""Headline bench: resolver throughput at 64K-txn batches.

The TPU conflict kernel versus the measured CPU baseline
(foundationdb_tpu/native — the stand-in for the reference's
`fdbserver -r skiplisttest` microbench, fdbserver/SkipList.cpp:1082-1177:
uniform 1M keyspace, one read + one write range per txn; snapshots lag up
to two batch-versions so reads really contend with history). Since r6
the default device path is the DELTA-TIERED kernel
(foundationdb_tpu.ops.delta — G-independent compile, delta-tier merges,
periodic compaction, optional read dedup); BENCH_KERNEL=classic runs the
r3-r5 single-tier mega-sort group kernel.

Prints ONE JSON line whose PRIMARY `value` is the TRANSFER-INCLUSIVE
pipelined rate (pack -> host->device copy -> kernel, overlapped by
TpuConflictSet.resolve_stream_pipelined) — the operative number a live
resolver fed by a proxy would see (VERDICT r5 task 2; the r3-r5 primary
was device-resident and is now the secondary `device_resident_txn_s`).

Phases: (1) CPU baseline timing + verdicts; (2) parity phase — the TPU
kernel resolves the same stream and decisions are asserted identical;
(3) device-resident pipelined throughput (kernel-only, inputs pre-staged
— the ablation ledger's "kernel" stage); (3b) PRIMARY transfer-inclusive
pipelined throughput + the per-stage ablation ledger
(pack / transfer / kernel / fence); (4) per-batch latency probe with
blocking calls, device-resident and transfer-inclusive.

Env overrides: BENCH_TXNS (default 65536), BENCH_BATCHES (default 32),
BENCH_CPU_BATCHES (default 4), BENCH_MODE (uniform | zipf | range —
BASELINE.json configs 1-3), BENCH_KERNEL (tiered | classic),
BENCH_FUSE (group size; tiered compiles ONCE for any value),
BENCH_DELTA_CAP, BENCH_COMPACT_INTERVAL, BENCH_REPS.

Flags: --profile-dir DIR captures a jax.profiler device/compile trace
of the PRIMARY measurement phase (TensorBoard/XProf xplanes);
--perf-ledger PATH / --no-perf control the perf-ledger row every run
appends to perf/history.jsonl (foundationdb_tpu/utils/perf.py).
"""

import argparse
import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--profile-dir", default=os.environ.get(
        "BENCH_PROFILE_DIR") or None,
        help="capture a jax.profiler trace of the primary phase here")
    ap.add_argument("--perf-ledger", default=None,
                    help="append the run's perf record to this JSONL "
                         "(default: perf/history.jsonl)")
    ap.add_argument("--no-perf", action="store_true",
                    help="skip the perf-ledger append")
    args = ap.parse_args()
    n_txns = int(os.environ.get("BENCH_TXNS", 65536))
    # 32-batch default (r5): the stream is long enough that per-fence
    # startup noise amortizes — measured 3.41x (32) vs 3.19x (16) on
    # back-to-back runs with overlapping device spreads; the CPU
    # baseline runs the SAME longer stream. "batches" ships in the JSON.
    n_batches = int(os.environ.get("BENCH_BATCHES", 32))
    cpu_batches = int(os.environ.get("BENCH_CPU_BATCHES", 4))
    mode = os.environ.get("BENCH_MODE", "uniform")
    keyspace = 1_000_000
    version_step = 200_000
    window = 1_000_000  # floor rises after 5 batches -> steady-state GC
    snapshot_lag = 2 * version_step  # spans ~2 batches: history conflicts real
    # BASELINE configs 1-3 plus the YCSB letter suite (ISSUE 14 —
    # workload breadth: B/C/D are zipf point mixes at different write
    # rates / recency, E is the range-scan-heavy profile the router
    # used to exile to the CPU skiplist; with the sorted-endpoint sweep
    # configured it stays on device and this bench re-measures that
    # routing every run)
    gen_kw = {
        "uniform": {},
        "zipf": {"zipf": 1.1, "keyspace": 10_000_000},  # hot-key contention
        "range": {"range_len": 500},  # wide scans vs point-ish writes
        "ycsb_b": {"zipf": 1.1, "keyspace": 10_000_000},
        "ycsb_c": {"zipf": 1.1, "keyspace": 10_000_000},
        "ycsb_d": {"keyspace": 10_000_000},
        "ycsb_e": {"zipf": 1.1, "scan_max": 100},
    }[mode]
    ycsb = mode.startswith("ycsb")
    # Fixpoint unroll depth per contention profile: measured convergence
    # depth (scripts/iters_model.py: uniform 3, zipf 6, range 12) plus
    # margin. fixpoint_latch drops the residual while_loop (~50ms/group
    # of XLA pessimization at ZERO iterations); a deeper-than-unroll
    # chain trips the unconverged latch and this script re-runs the
    # stream on the exact while kernel — loud fallback, never wrong.
    # Fixpoint depth per mode: the idealized model (scripts/
    # iters_model.py) says uniform 3 / zipf 6 / range 12, but the REAL
    # uniform stream's history masks deepen chains past 4 (the r4 latch
    # tripped at 3 and 4). r4 ran uniform on the EXACT kernel because at
    # the old per-application cost unroll>=5 broke even with the
    # residual while — and the r5 attempt (latched unroll 6 + the
    # prefix-count cross) MEASURED 702K txn/s vs the exact path's
    # 891-973K, so uniform stays on the EXACT kernel. zipf/range keep
    # the latch with margin; a trip falls back to the exact kernel
    # (loud, never wrong — the warm pass checks before any timed pass,
    # and prewarm_exact makes the swap compile-free).
    unroll = {"uniform": 3, "zipf": 8, "range": 14, "ycsb_b": 8,
              "ycsb_c": 3, "ycsb_d": 8, "ycsb_e": 14}[mode]
    latch = mode != "uniform"
    kernel = os.environ.get("BENCH_KERNEL", "tiered")
    # ycsb_e arms the ISSUE-14 device-native range path: the
    # sorted-endpoint sweep probe + spill-and-compact pressure handling
    # (both tiered-only; BENCH_SWEEP=0 ablates back to the probe path)
    sweep = (
        mode == "ycsb_e" and kernel == "tiered"
        and os.environ.get("BENCH_SWEEP", "1") != "0"
    )

    import jax

    from foundationdb_tpu.utils import compile_cache, perf

    cache_dir = compile_cache.enable()
    log(f"compilation cache: {cache_dir}")
    # the FULL device fingerprint (r10 satellite): `backend` alone made
    # CPU-host and v5e ledger rows indistinguishable to a comparator
    fingerprint = perf.device_fingerprint()
    log(f"fingerprint: {fingerprint}")

    from foundationdb_tpu.config import KernelConfig
    from foundationdb_tpu.models.conflict_set import TpuConflictSet
    from foundationdb_tpu.testing.benchgen import skiplist_style_batch

    log(f"devices: {jax.devices()}")
    cap = 1 << (n_txns - 1).bit_length()
    # hard bound on live boundaries: a range contributes its begin
    # (live) plus its end (carrier of the prior value), and the GC
    # floor trails one batch behind the newest — so
    # 2*writes/batch x (window/step + 1) = 12*cap live rows worst
    # case (coalescing only shrinks it; overflow raises, never lies —
    # 10*cap overflowed at BENCH_TXNS=16384 where uniform ranges
    # barely coalesce)
    hist_cap = 12 * cap
    # delta tier sized for the same window-worst-case (compaction every
    # group trims it back; occupancy scales with DISTINCT written
    # boundaries, so zipf keeps it tiny — the ledger reports both)
    delta_cap = int(os.environ.get("BENCH_DELTA_CAP", hist_cap))
    # group size for fused dispatch (also the default compaction
    # cadence: compact_interval counts BATCHES, so one compaction per
    # fused group). The tiered kernel compiles once for ANY value.
    fuse = max(1, int(os.environ.get("BENCH_FUSE", 8)))
    compact_interval = int(os.environ.get("BENCH_COMPACT_INTERVAL", fuse))
    config = KernelConfig(
        max_key_bytes=8,
        max_txns=cap,
        max_reads=cap,
        max_writes=cap,
        # short_span_limit stays 0: the direct short-span range ops
        # measured SLOWER than the doubling tables at these shapes
        # (scripts/profile_group.py ablations) — the option remains for
        # other shapes/platforms, latched and parity-tested.
        history_capacity=hist_cap,
        window_versions=window,
        fixpoint_unroll=unroll,
        fixpoint_latch=latch,
        delta_capacity=delta_cap if kernel == "tiered" else 0,
        compact_interval=compact_interval,
        range_sweep=sweep,
        delta_spill=sweep,
    )
    import dataclasses as _dc

    from foundationdb_tpu.testing.benchgen import ycsb_batch

    rng = np.random.default_rng(0)
    batches = []
    # ycsb_d read-latest insert frontier — from the MODE's keyspace
    # (gen_kw overrides the module default for the zipf-family modes)
    frontier = gen_kw.get("keyspace", keyspace) // 2
    for i in range(n_batches):
        version = (i + 1) * version_step
        kw = {"keyspace": keyspace, **gen_kw}
        if ycsb:
            b = ycsb_batch(
                rng, config, n_txns, mode, version=version, key_bytes=8,
                snapshot_lag=snapshot_lag, insert_frontier=frontier, **kw,
            )
            frontier += b.n_writes
        else:
            b = skiplist_style_batch(
                rng, config, n_txns, version=version,
                key_bytes=8, snapshot_lag=snapshot_lag, **kw,
            )
        batches.append(b)
    log(f"generated {n_batches} batches of {n_txns} txns")

    # the router re-measure (ISSUE 14): the stream's classified profile
    # and the backend the config-aware router would choose — ycsb_e must
    # classify range_heavy and STAY on device when the sweep is
    # configured (the no-fallback acceptance direction)
    from foundationdb_tpu.models.conflict_set import (
        backend_for_profile,
        profile_batch,
    )

    stream_profile = profile_batch(batches[0])
    routed_backend = backend_for_profile(stream_profile, config)
    log(f"contention profile: {stream_profile} -> routed {routed_backend}")
    if sweep:
        assert stream_profile == "range_heavy", stream_profile
        assert routed_backend == "tpu", (
            "range_heavy must stay on device with the sweep configured"
        )

    # Device-side read dedup (tiered only): size the distinct-range cap
    # from the ACTUAL stream — the max per-batch distinct (begin, end)
    # count, next power of two. Worth compiling only when duplicates are
    # common (zipf); a uniform stream's distinct count ~= its point
    # count, so dedup would add sorts for nothing and stays off.
    dedup = 0
    if kernel == "tiered" and not sweep:
        # (sweep-configured streams skip dedup: the endpoint sweep has
        # no per-range searches to dedup and the knobs are exclusive)
        max_uniq = 0
        for b in batches:
            pairs = np.concatenate(
                [b.read_begin[: b.n_reads], b.read_end[: b.n_reads]], axis=1
            )
            max_uniq = max(max_uniq, len(np.unique(pairs, axis=0)))
        if max_uniq <= cap // 2:
            dedup = 1 << (max_uniq - 1).bit_length()
            config = _dc.replace(config, dedup_reads=dedup)
        log(f"read dedup: max distinct ranges/batch {max_uniq} of {n_txns} "
            f"-> dedup_reads={dedup}")

    exact_config = _dc.replace(config, fixpoint_latch=False, dedup_reads=0)

    # ---- CPU baselines (native C++ ConflictBatch-equivalents) -----------
    # Two independent implementations (VERDICT r1 task 3): the ordered-map
    # semantic model and the skip-list port of the reference's algorithm
    # class (pyramid max-versions, radix point sort, bitset intra sweep).
    # vs_baseline is reported against the FASTER of the two.
    from foundationdb_tpu.native import (
        NativeConflictSet,
        NativeSkipListConflictSet,
    )

    from foundationdb_tpu.testing.benchgen import flatten_for_native as flat

    flats = [(flat(b, "r"), flat(b, "w")) for b in batches]

    def cpu_pass(cls, collect_verdicts=False):
        """One full stream through a fresh CPU conflict set; returns the
        steady-state rate (and optionally the first batches' verdicts)."""
        cpu = cls(window=window)
        cpu_times = []
        verdicts = []
        for i, b in enumerate(batches):
            (rkeys, roff, rtxn), (wkeys, woff, wtxn) = flats[i]
            snaps = b.snapshot[:n_txns].astype(np.int64)
            t0 = time.perf_counter()
            v = cpu.resolve_raw(
                int(b.version), snaps, rkeys, roff, rtxn, wkeys, woff, wtxn
            )
            cpu_times.append(time.perf_counter() - t0)
            if collect_verdicts and i < cpu_batches:
                verdicts.append(v)
        # steady-state rate: skip the warm-up batches before the window fills
        steady = cpu_times[len(cpu_times) // 2 :]
        return n_txns * len(steady) / sum(steady), verdicts

    # one verdict-collecting pass per impl up front: the two baselines
    # must agree before either is a baseline (timing comes later,
    # interleaved with the device passes — see the measurement phase)
    _, cpu_verdicts = cpu_pass(NativeConflictSet, collect_verdicts=True)
    _, sk_verdicts = cpu_pass(NativeSkipListConflictSet, collect_verdicts=True)
    for i in range(cpu_batches):
        assert (cpu_verdicts[i] == sk_verdicts[i]).all(), \
            f"cpu baseline disagreement at batch {i}"

    # ---- phase 1.5: rangemax flat-gather selftest on THIS device --------
    # The doubling-table query uses a flattened data-dependent gather; an
    # older XLA:TPU was seen miscompiling that pattern at large m (gather
    # landing on the wrong level). This randomized large-m check runs on
    # the real device every bench run so a regression trips loudly here,
    # before any throughput number is produced.
    from foundationdb_tpu.ops import rangemax as _rm

    mm = config.history_capacity
    _rm.flat_gather_selftest(mm, force=True)
    log(f"rangemax large-m selftest: OK (m={mm}, 8192 queries)")

    # ---- phase 2: decision parity ---------------------------------------
    cs = TpuConflictSet(config)
    t0 = time.perf_counter()
    for i in range(cpu_batches):
        out = cs.resolve_packed(batches[i])
        dv = np.asarray(out.verdict)[:n_txns]
        n_commit = int((dv == 3).sum())
        n_conflict = int((dv == 0).sum())
        assert (dv == cpu_verdicts[i]).all(), f"decision mismatch at batch {i}"
    log(f"decision parity: OK ({cpu_batches} batches, last: "
        f"{n_commit} committed / {n_conflict} conflicted; "
        f"incl. compile {time.perf_counter() - t0:.1f}s)")

    # ---- phase 3: pipelined throughput ----------------------------------
    # Batches are staged on device untimed. Rationale: on a real TPU host
    # the per-batch host->device hop is PCIe (~7MB => well under 1ms,
    # negligible against a >100ms kernel); in THIS environment the hop
    # rides a dev tunnel with ~100ms+ RTT that no production deployment
    # pays. Staging measures the resolver, not the tunnel. The CPU
    # baseline's inputs are likewise in RAM before its timer starts.
    # Phase 4 reports the tunnel-inclusive latency separately so the
    # staging effect is visible, and the JSON marks the methodology.
    # Batches are dispatched in groups of BENCH_FUSE (default 8) through
    # the GROUP kernel (ops/group.py): one mega-sort program resolves the
    # whole group — identical decisions (tests/test_group_parity.py), one
    # dispatch per group (~76ms through this environment's tunnel), and
    # the history merge amortized across the group. A loaded resolver
    # coalescing its queue is exactly how the reference behaves under
    # backpressure (fdbserver/Resolver.actor.cpp resolveBatch queueing).
    # Per-batch latency is still reported un-fused (phase 4). Classic
    # kernel: 8 batches per group — G=16 amortizes fixed costs further
    # but its XLA compile exceeds 35 minutes on a single-core host. The
    # tiered kernel has no such wall (G-independent body; BENCH_FUSE up
    # to MAX_GROUP_TIERED=64, compile probe logs the flat curve).
    from foundationdb_tpu.utils.packing import stack_device_args

    dev_groups = [
        jax.device_put(stack_device_args(batches[g : g + fuse]))
        for g in range(0, n_batches, fuse)
    ]
    jax.block_until_ready(dev_groups)
    # warm the group program for every group shape (the ragged tail group
    # compiles separately) so compilation stays out of the timed window
    warm = TpuConflictSet(config)
    for dg in {g["version"].shape[0]: g for g in dev_groups}.values():
        t0 = time.perf_counter()
        warm.resolve_group_args(dg, check_latch=False)
        jax.block_until_ready(warm.state)
        log(f"warm compile G={dg['version'].shape[0]}: "
            f"{time.perf_counter() - t0:.1f}s")
        # latch mode: pre-warm the exact while-loop program for the same
        # shape so a mid-stream latch trip swaps programs instead of
        # paying an XLA compile inside a timed rep (VERDICT r4 task 5)
        warm.prewarm_exact(dg)
    jax.block_until_ready(warm.state)

    # HLO cost-model extraction (ISSUE 10): FLOPs / bytes accessed of
    # the compiled group program, per run — hardware sessions compare
    # achieved rate against this roofline. Warm signature => persistent
    # compile-cache hit, so this costs deserialization, not a compile.
    hlo_cost = warm.kernel_cost_analysis(dev_groups[0])
    log(f"kernel HLO cost model: {hlo_cost or 'unavailable'}")

    # G-independence probe (opt-in: BENCH_COMPILE_PROBE=1): compile the
    # SAME kernel at extra group sizes and log the wall time per G. The
    # tiered kernel's scan body is G-independent, so the curve is ~flat
    # where the classic skeleton's grew with G to a >35min wall at G=16
    # (ops/group.py MAX_GROUP note).
    if os.environ.get("BENCH_COMPILE_PROBE") and kernel == "tiered":
        # tiered only: probing the classic kernel at 2*fuse would pay
        # the exact >35-minute G-scaling compile wall the probe exists
        # to show is gone. Sizes clamp to the kernel's group cap.
        from foundationdb_tpu.ops.delta import MAX_GROUP_TIERED

        probe_cap = min(n_batches, MAX_GROUP_TIERED)
        for g_probe in sorted({2, fuse // 2, min(2 * fuse, probe_cap)}):
            if g_probe < 1 or g_probe == fuse or g_probe > probe_cap:
                continue
            probe_args = jax.device_put(
                stack_device_args(batches[:g_probe])
            )
            warm_p = TpuConflictSet(config)
            t0 = time.perf_counter()
            warm_p.resolve_group_args(probe_args, check_latch=False)
            jax.block_until_ready(warm_p.state)
            log(f"compile probe G={g_probe}: "
                f"{time.perf_counter() - t0:.1f}s wall (kernel={kernel})")
            del warm_p, probe_args

    def device_pass(check_parity=False, cfg_=None):
        cs2 = TpuConflictSet(cfg_ or config)
        outs = []
        t0 = time.perf_counter()
        for dg in dev_groups:
            # check_latch=False: the per-group latch sync would serialize
            # the async pipeline; this loop fences ONCE below and handles
            # an unconverged group itself (return None -> caller falls
            # back to the exact kernel)
            outs.append(cs2.resolve_group_args(dg, check_latch=False))
        np.asarray(outs[-1].verdict)  # honest fence: device->host transfer
        total = time.perf_counter() - t0
        cs2.check_overflow()
        # the latch-mode kernel REFUSES (does not mis-answer) chains
        # deeper than the unroll — and the tiered dedup latch refuses
        # batches with more distinct ranges than compiled for: check
        # after timing, fall back loudly
        if (
            (cfg_ or config).fixpoint_latch or (cfg_ or config).dedup_reads
        ) and any(
            bool(np.asarray(o.unconverged).any()) for o in outs
        ):
            return None
        if check_parity:
            # decision parity of the fused path against the CPU verdicts
            for i in range(cpu_batches):
                dv = np.asarray(outs[i // fuse].verdict[i % fuse])[:n_txns]
                assert (dv == cpu_verdicts[i]).all(), \
                    f"fused-path decision mismatch at batch {i}"
        return n_txns * n_batches / total

    if device_pass(check_parity=True) is None:  # warm + parity, untimed
        log("fixpoint latch tripped: falling back to the exact "
            "while-loop kernel for the measured passes")
        config = exact_config
        warm2 = TpuConflictSet(config)
        for dg in {g["version"].shape[0]: g for g in dev_groups}.values():
            warm2.resolve_group_args(dg)
        jax.block_until_ready(warm2.state)
        assert device_pass(check_parity=True) is not None

    # INTERLEAVED median-of-N measurement (VERDICT r3 weak #4): the
    # shared-host CPU baseline swings >2x run-to-run, so a single draw of
    # each side makes the graded ratio a dice roll. Alternating
    # cpu/device passes sample the same noise environment; medians of
    # each side are the numbers of record and the spreads ship in the
    # JSON. (Core pinning is moot here: the host has ONE core.)
    reps = max(1, int(os.environ.get("BENCH_REPS", 5)))
    cpu_samples = {"map": [], "skiplist": []}
    dev_samples = []
    for rep in range(reps):
        cpu_samples["map"].append(cpu_pass(NativeConflictSet)[0])
        d = device_pass()
        # reps replay the identical pre-staged groups, so a latch trip
        # here would contradict the clean warm pass above — fail loudly
        # rather than let None poison the median (ADVICE r4)
        assert d is not None, "latch tripped mid-rep on a warm-clean stream"
        dev_samples.append(d)
        cpu_samples["skiplist"].append(
            cpu_pass(NativeSkipListConflictSet)[0]
        )
        log(f"rep {rep}: cpu map {cpu_samples['map'][-1]:,.0f} | "
            f"skiplist {cpu_samples['skiplist'][-1]:,.0f} | "
            f"device {dev_samples[-1]:,.0f} txn/s")

    med = lambda xs: sorted(xs)[len(xs) // 2]
    cpu_medians = {k: med(v) for k, v in cpu_samples.items()}
    cpu_name, cpu_rate = max(cpu_medians.items(), key=lambda kv: kv[1])
    dev_rate = med(dev_samples)
    log(f"baseline of record: {cpu_name} median {cpu_rate:,.0f} txn/s "
        f"(spread {min(cpu_samples[cpu_name]):,.0f}-"
        f"{max(cpu_samples[cpu_name]):,.0f}); device median "
        f"{dev_rate:,.0f} (spread {min(dev_samples):,.0f}-"
        f"{max(dev_samples):,.0f})")

    # ---- phase 3b: PRIMARY — transfer-inclusive pipelined throughput ----
    # The operative number (VERDICT r5 task 2): batches start HOST-side
    # as packed tensors every rep, and the timed region covers the full
    # pack (group stacking) -> host->device copy -> kernel pipeline.
    # TpuConflictSet.resolve_stream_pipelined stages at sub-group depth
    # on a separate thread: the pack+copy of chunk k+1 overlaps the
    # compute of chunk k, so packing is off the critical thread and the
    # stream rate should approach the device-resident rate.
    latchy = config.fixpoint_latch or config.dedup_reads
    incl_samples = []
    # --profile-dir: the PRIMARY phase runs under a jax.profiler trace
    # (device/compile timelines per dispatch — the per-device timing
    # attribution the multi-chip shard work will need)
    with perf.profile_trace(args.profile_dir):
        for _rep in range(reps):
            cs_s = TpuConflictSet(config)
            t0 = time.perf_counter()
            outs_s = cs_s.resolve_stream_pipelined(batches, chunk=fuse)
            np.asarray(outs_s[-1].verdict)  # honest fence
            total = time.perf_counter() - t0
            if latchy and any(
                bool(np.asarray(o.unconverged).any()) for o in outs_s
            ):
                log("phase 3b: latch tripped; skipping incl-transfer sample")
                continue
            incl_samples.append(n_txns * n_batches / total)
    if args.profile_dir:
        log(f"jax.profiler trace captured in {args.profile_dir}")
    incl_rate = med(incl_samples) if incl_samples else 0.0
    log(f"PRIMARY incl-transfer pipelined (pack->copy->compute overlap): "
        f"{incl_rate:,.0f} txn/s ({len(incl_samples)} reps, "
        f"spread {min(incl_samples):,.0f}-{max(incl_samples):,.0f})"
        if incl_samples else "PRIMARY incl-transfer pipelined: NO SAMPLES")

    # ---- phase 3c: per-stage ablation ledger ---------------------------
    # READER of the shared instrumentation (ISSUE 5): the stage timers,
    # merge-row accounting and tier-occupancy pass all live in
    # models/conflict_set.py (KernelStageMetrics + stage_ledger) — the
    # same metrics a live resolver emits continuously; this script owns
    # no private timers. kernel_s is the phase-3 device-resident
    # measurement; pipelined_s the phase-3b transfer-inclusive one.
    from foundationdb_tpu.models.conflict_set import stage_ledger

    ledger = stage_ledger(
        config,
        batches,
        fuse=fuse,
        kernel_s=n_txns * n_batches / dev_rate,
        pipelined_s=(n_txns * n_batches / incl_rate) if incl_rate else 0.0,
        occupancy_delta_capacity=hist_cap,
    )
    log(f"ablation ledger: {json.dumps(ledger)}")

    # ---- structural decision + range-path accounting (ISSUE 14) ---------
    # One more clean pass over the pre-staged groups, untimed: total
    # commit/abort decisions plus the sweep/spill counters — all
    # deterministic given the seeded stream, so the perfcheck lane gates
    # them exactly on any host (a flipped verdict or a silently
    # re-routed probe path fails CI before hardware ever re-measures).
    cs_m = TpuConflictSet(config)
    decisions = {"committed": 0, "conflicted": 0, "too_old": 0}
    for dg in dev_groups:
        o = cs_m.resolve_group_args(dg, check_latch=False)
        decisions["committed"] += int(np.asarray(o.committed_count).sum())
        decisions["conflicted"] += int(np.asarray(o.conflict_count).sum())
        decisions["too_old"] += int(np.asarray(o.too_old_count).sum())
    cs_m.check_overflow()
    _c = cs_m.metrics.counters
    structural = {
        **decisions,
        "spills": _c.get("spills"),
        "sweep_groups": _c.get("sweepGroups"),
        "compactions": _c.get("compactions"),
    }
    if getattr(config, "range_sweep", False):
        from foundationdb_tpu.ops.delta import sweep_rows_per_group

        structural["sweep_rows_per_group"] = sweep_rows_per_group(
            config.history_capacity, fuse, config.max_reads
        )
    log(f"structural: {json.dumps(structural)}")

    # ---- phase 4: per-batch latency probe -------------------------------
    del dev_groups  # release phase-3 staging before re-staging
    dev_batches = [jax.device_put(b.device_args()) for b in batches]
    jax.block_until_ready(dev_batches)
    # compact_interval counts batches, so these per-batch dispatches
    # already pay compaction at the same cadence as the fused stream
    cs3 = TpuConflictSet(config)
    lat = []
    for db in dev_batches:
        t0 = time.perf_counter()
        out = cs3.resolve_args(db)
        np.asarray(out.verdict)  # honest fence (block_until_ready lies
        #                          through the tunnel — see memory/r3)
        lat.append(time.perf_counter() - t0)
    lat_s = sorted(lat[1:])
    p50 = lat_s[len(lat_s) // 2]
    p99 = lat_s[min(len(lat_s) - 1, int(len(lat_s) * 0.99))]

    # Same probe with the host->device transfer inside the timed region
    # (what a caller on THIS machine, through the tunnel, would see).
    cs4 = TpuConflictSet(config)
    lat_h = []
    for b in batches:
        t0 = time.perf_counter()
        out = cs4.resolve_packed(b)
        np.asarray(out.verdict)
        lat_h.append(time.perf_counter() - t0)
    lat_hs = sorted(lat_h[1:])
    p50_h = lat_hs[len(lat_hs) // 2]

    log(
        f"device: {dev_rate:,.0f} txn/s pipelined | kernel latency p50 "
        f"{p50*1e3:.0f}ms p99 {p99*1e3:.0f}ms | incl. host->device transfer "
        f"p50 {p50_h*1e3:.0f}ms | speedup {dev_rate / cpu_rate:.2f}x"
    )

    # ---- phase 5 (opt-in): small-batch latency sweep --------------------
    # BENCH_SMALL=1: the reference's resolver lives on a <3ms commit path
    # (performance.rst:49; Resolver.actor.cpp:174-208 latency histograms)
    # at batches of hundreds-to-thousands of txns. Measure that regime
    # honestly: device p50 (resident + transfer-inclusive) vs the CPU
    # backends on identical small batches. These numbers set the
    # RESOLVER_TPU_MIN_BATCH auto-routing knob (utils/knobs.py): below
    # the threshold the CPU resolves before the device dispatch returns.
    small = {}
    if os.environ.get("BENCH_SMALL"):
        for n_small in (512, 2048):
            cap_s = 4096
            cfg_s = KernelConfig(
                max_key_bytes=8, max_txns=cap_s, max_reads=cap_s,
                max_writes=cap_s, history_capacity=12 * cap_s,
                window_versions=window,
            )
            sb = [
                skiplist_style_batch(
                    rng, cfg_s, n_small, version=(i + 1) * version_step,
                    key_bytes=8, snapshot_lag=snapshot_lag,
                    keyspace=keyspace,
                )
                for i in range(12)
            ]
            css = TpuConflictSet(cfg_s)
            dev_sb = [jax.device_put(b.device_args()) for b in sb]
            jax.block_until_ready(dev_sb)
            lat_d, lat_t = [], []
            for db_, b in zip(dev_sb, sb):
                t0 = time.perf_counter()
                np.asarray(css.resolve_args(db_).verdict)
                lat_d.append(time.perf_counter() - t0)
            css2 = TpuConflictSet(cfg_s)
            for b in sb:
                t0 = time.perf_counter()
                np.asarray(css2.resolve_packed(b).verdict)
                lat_t.append(time.perf_counter() - t0)
            cpu_s = NativeSkipListConflictSet(window=window)
            lat_c = []
            for b in sb:
                (rk, ro, rt), (wk, wo, wt) = flat(b, "r"), flat(b, "w")
                t0 = time.perf_counter()
                cpu_s.resolve_raw(
                    int(b.version), b.snapshot[:n_small].astype(np.int64),
                    rk, ro, rt, wk, wo, wt,
                )
                lat_c.append(time.perf_counter() - t0)
            m_ = lambda xs: sorted(xs[1:])[len(xs[1:]) // 2]
            small[str(n_small)] = {
                "device_p50_ms": round(m_(lat_d) * 1e3, 2),
                "device_incl_transfer_p50_ms": round(m_(lat_t) * 1e3, 2),
                "cpu_skiplist_p50_ms": round(m_(lat_c) * 1e3, 2),
            }
            log(f"small-batch n={n_small}: {small[str(n_small)]}")

    suffix = "" if mode == "uniform" else f"_{mode}"
    cc_stats = compile_cache.stats()
    row = {
        "metric": f"resolver_txns_per_sec_{n_txns // 1024}k_batch{suffix}",
        # PRIMARY (r6, VERDICT r5 task 2): the transfer-inclusive
        # pipelined rate — pack + host->device copy + kernel,
        # overlapped. The r3-r5 primary (device-resident) ships
        # as device_resident_txn_s; "staging": "pipelined" marks
        # the methodology switch (BASELINE.md note).
        "value": round(incl_rate, 1),
        "unit": "txn/s",
        "vs_baseline": round(incl_rate / cpu_rate, 3),
        "baseline": cpu_name,
        "baseline_txns_per_sec": round(cpu_rate, 1),
        "reps": reps,
        "baseline_spread": [
            round(min(cpu_samples[cpu_name]), 1),
            round(max(cpu_samples[cpu_name]), 1),
        ],
        "device_resident_txn_s": round(dev_rate, 1),
        "device_resident_vs_baseline": round(dev_rate / cpu_rate, 3),
        "device_spread": [
            round(min(dev_samples), 1),
            round(max(dev_samples), 1),
        ],
        "incl_spread": [
            round(min(incl_samples), 1),
            round(max(incl_samples), 1),
        ] if incl_samples else [],
        "staging": "pipelined",
        "backend": jax.default_backend(),
        # full device fingerprint (kind/count/jaxlib): without
        # it CPU-host and v5e rows are indistinguishable to the
        # perfcheck comparator
        "device": fingerprint,
        "compile_cache": cc_stats,
        "hlo_cost": hlo_cost,
        "kernel": kernel,
        "delta_capacity": config.delta_capacity,
        "dedup_reads": config.dedup_reads,
        "range_sweep": config.range_sweep,
        "delta_spill": config.delta_spill,
        "compact_interval": config.compact_interval,
        "profile": stream_profile,
        "routed_backend": routed_backend,
        "structural": structural,
        "fused_dispatch": fuse,
        "batches": n_batches,
        "p50_ms": round(p50 * 1e3, 1),
        "p99_ms": round(p99 * 1e3, 1),
        "p50_incl_transfer_ms": round(p50_h * 1e3, 1),
        "ablation": ledger,
        **({"small_batch": small} if small else {}),
    }
    print(json.dumps(row))
    # the canonical perf-ledger row (utils/perf.py): the printed JSON
    # stays the human/driver view; the ledger is what perfcheck gates
    if not args.no_perf:
        rec = perf.bench_row_to_record(row, fingerprint=fingerprint)
        path = perf.append(rec, path=args.perf_ledger)
        log(f"perf ledger row appended to {path}")


if __name__ == "__main__":
    main()
