#!/usr/bin/env python
"""Headline bench: resolver throughput at 64K-txn batches.

The TPU conflict kernel (foundationdb_tpu.ops.conflict.resolve_batch,
replacing fdbserver/SkipList.cpp detectConflicts) versus the measured CPU
baseline (foundationdb_tpu/native — the stand-in for the reference's
`fdbserver -r skiplisttest` microbench, fdbserver/SkipList.cpp:1082-1177:
uniform 1M keyspace, one read + one write range per txn; snapshots lag up
to two batch-versions so reads really contend with history).

Prints ONE JSON line:
  {"metric": ..., "value": txns/s on device, "unit": "txn/s",
   "vs_baseline": device_rate / cpu_baseline_rate}

Phases: (1) CPU baseline timing + verdicts; (2) parity phase — the TPU
kernel resolves the same stream and decisions are asserted identical;
(3) pipelined throughput — a fresh kernel instance re-runs the stream
with async dispatch (state donation chains batches on-device), inputs
pre-staged on device (see the phase-3 comment for why that is the honest
framing in this environment; the JSON line carries
"staging": "device" so runs before/after this methodology are not
conflated); (4) per-batch latency probe with blocking calls, reported
both with device-resident inputs (kernel latency) and with the
host->device transfer included (tunnel-inclusive latency).

Env overrides: BENCH_TXNS (default 65536), BENCH_BATCHES (default 16),
BENCH_CPU_BATCHES (default 4), BENCH_MODE (uniform | zipf | range —
BASELINE.json configs 1-3: uniform 1M keyspace; Zipf-0.99-style hot-key
contention; wide range reads vs point writes).
"""

import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    n_txns = int(os.environ.get("BENCH_TXNS", 65536))
    # 32-batch default (r5): the stream is long enough that per-fence
    # startup noise amortizes — measured 3.41x (32) vs 3.19x (16) on
    # back-to-back runs with overlapping device spreads; the CPU
    # baseline runs the SAME longer stream. "batches" ships in the JSON.
    n_batches = int(os.environ.get("BENCH_BATCHES", 32))
    cpu_batches = int(os.environ.get("BENCH_CPU_BATCHES", 4))
    mode = os.environ.get("BENCH_MODE", "uniform")
    keyspace = 1_000_000
    version_step = 200_000
    window = 1_000_000  # floor rises after 5 batches -> steady-state GC
    snapshot_lag = 2 * version_step  # spans ~2 batches: history conflicts real
    gen_kw = {
        "uniform": {},
        "zipf": {"zipf": 1.1, "keyspace": 10_000_000},  # hot-key contention
        "range": {"range_len": 500},  # wide scans vs point-ish writes
    }[mode]
    # Fixpoint unroll depth per contention profile: measured convergence
    # depth (scripts/iters_model.py: uniform 3, zipf 6, range 12) plus
    # margin. fixpoint_latch drops the residual while_loop (~50ms/group
    # of XLA pessimization at ZERO iterations); a deeper-than-unroll
    # chain trips the unconverged latch and this script re-runs the
    # stream on the exact while kernel — loud fallback, never wrong.
    # Fixpoint depth per mode: the idealized model (scripts/
    # iters_model.py) says uniform 3 / zipf 6 / range 12, but the REAL
    # uniform stream's history masks deepen chains past 4 (the r4 latch
    # tripped at 3 and 4). r4 ran uniform on the EXACT kernel because at
    # the old per-application cost unroll>=5 broke even with the
    # residual while — and the r5 attempt (latched unroll 6 + the
    # prefix-count cross) MEASURED 702K txn/s vs the exact path's
    # 891-973K, so uniform stays on the EXACT kernel. zipf/range keep
    # the latch with margin; a trip falls back to the exact kernel
    # (loud, never wrong — the warm pass checks before any timed pass,
    # and prewarm_exact makes the swap compile-free).
    unroll = {"uniform": 3, "zipf": 8, "range": 14}[mode]
    latch = mode != "uniform"

    import jax

    from foundationdb_tpu.utils import compile_cache

    cache_dir = compile_cache.enable()
    log(f"compilation cache: {cache_dir}")

    from foundationdb_tpu.config import KernelConfig
    from foundationdb_tpu.models.conflict_set import TpuConflictSet
    from foundationdb_tpu.testing.benchgen import skiplist_style_batch

    log(f"devices: {jax.devices()}")
    cap = 1 << (n_txns - 1).bit_length()
    config = KernelConfig(
        max_key_bytes=8,
        max_txns=cap,
        max_reads=cap,
        max_writes=cap,
        # short_span_limit stays 0: the direct short-span range ops
        # measured SLOWER than the doubling tables at these shapes
        # (scripts/profile_group.py ablations) — the option remains for
        # other shapes/platforms, latched and parity-tested.
        # hard bound on live boundaries: a range contributes its begin
        # (live) plus its end (carrier of the prior value), and the GC
        # floor trails one batch behind the newest — so
        # 2*writes/batch x (window/step + 1) = 12*cap live rows worst
        # case (coalescing only shrinks it; overflow raises, never lies —
        # 10*cap overflowed at BENCH_TXNS=16384 where uniform ranges
        # barely coalesce)
        history_capacity=12 * cap,
        window_versions=window,
        fixpoint_unroll=unroll,
        fixpoint_latch=latch,
    )
    import dataclasses as _dc

    exact_config = _dc.replace(config, fixpoint_latch=False)

    rng = np.random.default_rng(0)
    batches = []
    for i in range(n_batches):
        version = (i + 1) * version_step
        kw = {"keyspace": keyspace, **gen_kw}
        batches.append(
            skiplist_style_batch(
                rng, config, n_txns, version=version,
                key_bytes=8, snapshot_lag=snapshot_lag, **kw,
            )
        )
    log(f"generated {n_batches} batches of {n_txns} txns")

    # ---- CPU baselines (native C++ ConflictBatch-equivalents) -----------
    # Two independent implementations (VERDICT r1 task 3): the ordered-map
    # semantic model and the skip-list port of the reference's algorithm
    # class (pyramid max-versions, radix point sort, bitset intra sweep).
    # vs_baseline is reported against the FASTER of the two.
    from foundationdb_tpu.native import (
        NativeConflictSet,
        NativeSkipListConflictSet,
    )

    from foundationdb_tpu.testing.benchgen import flatten_for_native as flat

    flats = [(flat(b, "r"), flat(b, "w")) for b in batches]

    def cpu_pass(cls, collect_verdicts=False):
        """One full stream through a fresh CPU conflict set; returns the
        steady-state rate (and optionally the first batches' verdicts)."""
        cpu = cls(window=window)
        cpu_times = []
        verdicts = []
        for i, b in enumerate(batches):
            (rkeys, roff, rtxn), (wkeys, woff, wtxn) = flats[i]
            snaps = b.snapshot[:n_txns].astype(np.int64)
            t0 = time.perf_counter()
            v = cpu.resolve_raw(
                int(b.version), snaps, rkeys, roff, rtxn, wkeys, woff, wtxn
            )
            cpu_times.append(time.perf_counter() - t0)
            if collect_verdicts and i < cpu_batches:
                verdicts.append(v)
        # steady-state rate: skip the warm-up batches before the window fills
        steady = cpu_times[len(cpu_times) // 2 :]
        return n_txns * len(steady) / sum(steady), verdicts

    # one verdict-collecting pass per impl up front: the two baselines
    # must agree before either is a baseline (timing comes later,
    # interleaved with the device passes — see the measurement phase)
    _, cpu_verdicts = cpu_pass(NativeConflictSet, collect_verdicts=True)
    _, sk_verdicts = cpu_pass(NativeSkipListConflictSet, collect_verdicts=True)
    for i in range(cpu_batches):
        assert (cpu_verdicts[i] == sk_verdicts[i]).all(), \
            f"cpu baseline disagreement at batch {i}"

    # ---- phase 1.5: rangemax flat-gather selftest on THIS device --------
    # The doubling-table query uses a flattened data-dependent gather; an
    # older XLA:TPU was seen miscompiling that pattern at large m (gather
    # landing on the wrong level). This randomized large-m check runs on
    # the real device every bench run so a regression trips loudly here,
    # before any throughput number is produced.
    from foundationdb_tpu.ops import rangemax as _rm

    mm = config.history_capacity
    _rm.flat_gather_selftest(mm, force=True)
    log(f"rangemax large-m selftest: OK (m={mm}, 8192 queries)")

    # ---- phase 2: decision parity ---------------------------------------
    cs = TpuConflictSet(config)
    t0 = time.perf_counter()
    for i in range(cpu_batches):
        out = cs.resolve_packed(batches[i])
        dv = np.asarray(out.verdict)[:n_txns]
        n_commit = int((dv == 3).sum())
        n_conflict = int((dv == 0).sum())
        assert (dv == cpu_verdicts[i]).all(), f"decision mismatch at batch {i}"
    log(f"decision parity: OK ({cpu_batches} batches, last: "
        f"{n_commit} committed / {n_conflict} conflicted; "
        f"incl. compile {time.perf_counter() - t0:.1f}s)")

    # ---- phase 3: pipelined throughput ----------------------------------
    # Batches are staged on device untimed. Rationale: on a real TPU host
    # the per-batch host->device hop is PCIe (~7MB => well under 1ms,
    # negligible against a >100ms kernel); in THIS environment the hop
    # rides a dev tunnel with ~100ms+ RTT that no production deployment
    # pays. Staging measures the resolver, not the tunnel. The CPU
    # baseline's inputs are likewise in RAM before its timer starts.
    # Phase 4 reports the tunnel-inclusive latency separately so the
    # staging effect is visible, and the JSON marks the methodology.
    # Batches are dispatched in groups of BENCH_FUSE (default 8) through
    # the GROUP kernel (ops/group.py): one mega-sort program resolves the
    # whole group — identical decisions (tests/test_group_parity.py), one
    # dispatch per group (~76ms through this environment's tunnel), and
    # the history merge amortized across the group. A loaded resolver
    # coalescing its queue is exactly how the reference behaves under
    # backpressure (fdbserver/Resolver.actor.cpp resolveBatch queueing).
    # Per-batch latency is still reported un-fused (phase 4).
    # 8 batches per group: G=16 amortizes fixed costs further but its
    # XLA compile exceeds 35 minutes on a single-core host — not worth
    # the cold-start risk for ~10% throughput.
    fuse = max(1, int(os.environ.get("BENCH_FUSE", 8)))
    from foundationdb_tpu.utils.packing import stack_device_args

    dev_groups = [
        jax.device_put(stack_device_args(batches[g : g + fuse]))
        for g in range(0, n_batches, fuse)
    ]
    jax.block_until_ready(dev_groups)
    # warm the group program for every group shape (the ragged tail group
    # compiles separately) so compilation stays out of the timed window
    warm = TpuConflictSet(config)
    for dg in {g["version"].shape[0]: g for g in dev_groups}.values():
        warm.resolve_group_args(dg, check_latch=False)
        # latch mode: pre-warm the exact while-loop program for the same
        # shape so a mid-stream latch trip swaps programs instead of
        # paying an XLA compile inside a timed rep (VERDICT r4 task 5)
        warm.prewarm_exact(dg)
    jax.block_until_ready(warm.state)

    def device_pass(check_parity=False, cfg_=None):
        cs2 = TpuConflictSet(cfg_ or config)
        outs = []
        t0 = time.perf_counter()
        for dg in dev_groups:
            # check_latch=False: the per-group latch sync would serialize
            # the async pipeline; this loop fences ONCE below and handles
            # an unconverged group itself (return None -> caller falls
            # back to the exact kernel)
            outs.append(cs2.resolve_group_args(dg, check_latch=False))
        np.asarray(outs[-1].verdict)  # honest fence: device->host transfer
        total = time.perf_counter() - t0
        cs2.check_overflow()
        # the latch-mode kernel REFUSES (does not mis-answer) chains
        # deeper than the unroll: check after timing, fall back loudly
        if (cfg_ or config).fixpoint_latch and any(
            bool(np.asarray(o.unconverged).any()) for o in outs
        ):
            return None
        if check_parity:
            # decision parity of the fused path against the CPU verdicts
            for i in range(cpu_batches):
                dv = np.asarray(outs[i // fuse].verdict[i % fuse])[:n_txns]
                assert (dv == cpu_verdicts[i]).all(), \
                    f"fused-path decision mismatch at batch {i}"
        return n_txns * n_batches / total

    if device_pass(check_parity=True) is None:  # warm + parity, untimed
        log("fixpoint latch tripped: falling back to the exact "
            "while-loop kernel for the measured passes")
        config = exact_config
        warm2 = TpuConflictSet(config)
        for dg in {g["version"].shape[0]: g for g in dev_groups}.values():
            warm2.resolve_group_args(dg)
        jax.block_until_ready(warm2.state)
        assert device_pass(check_parity=True) is not None

    # INTERLEAVED median-of-N measurement (VERDICT r3 weak #4): the
    # shared-host CPU baseline swings >2x run-to-run, so a single draw of
    # each side makes the graded ratio a dice roll. Alternating
    # cpu/device passes sample the same noise environment; medians of
    # each side are the numbers of record and the spreads ship in the
    # JSON. (Core pinning is moot here: the host has ONE core.)
    reps = max(1, int(os.environ.get("BENCH_REPS", 5)))
    cpu_samples = {"map": [], "skiplist": []}
    dev_samples = []
    for rep in range(reps):
        cpu_samples["map"].append(cpu_pass(NativeConflictSet)[0])
        d = device_pass()
        # reps replay the identical pre-staged groups, so a latch trip
        # here would contradict the clean warm pass above — fail loudly
        # rather than let None poison the median (ADVICE r4)
        assert d is not None, "latch tripped mid-rep on a warm-clean stream"
        dev_samples.append(d)
        cpu_samples["skiplist"].append(
            cpu_pass(NativeSkipListConflictSet)[0]
        )
        log(f"rep {rep}: cpu map {cpu_samples['map'][-1]:,.0f} | "
            f"skiplist {cpu_samples['skiplist'][-1]:,.0f} | "
            f"device {dev_samples[-1]:,.0f} txn/s")

    med = lambda xs: sorted(xs)[len(xs) // 2]
    cpu_medians = {k: med(v) for k, v in cpu_samples.items()}
    cpu_name, cpu_rate = max(cpu_medians.items(), key=lambda kv: kv[1])
    dev_rate = med(dev_samples)
    log(f"baseline of record: {cpu_name} median {cpu_rate:,.0f} txn/s "
        f"(spread {min(cpu_samples[cpu_name]):,.0f}-"
        f"{max(cpu_samples[cpu_name]):,.0f}); device median "
        f"{dev_rate:,.0f} (spread {min(dev_samples):,.0f}-"
        f"{max(dev_samples):,.0f})")

    # ---- phase 3b: TRANSFER-INCLUSIVE pipelined throughput --------------
    # The r4 verdict's task 4: the timed phase-3 path pre-stages inputs;
    # a live resolver pays the host->device copy per group. Double-
    # buffered staging (TpuConflictSet.resolve_group_stream) overlaps
    # group g+1's copy with group g's compute, so the transfer-inclusive
    # stream rate should approach the device-resident rate. Measured
    # with the groups starting HOST-side every rep.
    host_groups = [
        stack_device_args(batches[g : g + fuse])
        for g in range(0, n_batches, fuse)
    ]
    incl_samples = []
    for _rep in range(min(3, reps)):
        cs_s = TpuConflictSet(config)
        t0 = time.perf_counter()
        outs_s = cs_s.resolve_group_stream(host_groups, check_latch=False)
        np.asarray(outs_s[-1].verdict)  # honest fence
        total = time.perf_counter() - t0
        if config.fixpoint_latch and any(
            bool(np.asarray(o.unconverged).any()) for o in outs_s
        ):
            log("phase 3b: latch tripped; skipping incl-transfer sample")
            continue
        incl_samples.append(n_txns * n_batches / total)
    incl_rate = med(incl_samples) if incl_samples else 0.0
    log(f"incl-transfer pipelined (double-buffered staging): "
        f"{incl_rate:,.0f} txn/s ({len(incl_samples)} reps)")

    # ---- phase 4: per-batch latency probe -------------------------------
    del dev_groups  # release phase-3 staging before re-staging
    dev_batches = [jax.device_put(b.device_args()) for b in batches]
    jax.block_until_ready(dev_batches)
    cs3 = TpuConflictSet(config)
    lat = []
    for db in dev_batches:
        t0 = time.perf_counter()
        out = cs3.resolve_args(db)
        np.asarray(out.verdict)  # honest fence (block_until_ready lies
        #                          through the tunnel — see memory/r3)
        lat.append(time.perf_counter() - t0)
    lat_s = sorted(lat[1:])
    p50 = lat_s[len(lat_s) // 2]
    p99 = lat_s[min(len(lat_s) - 1, int(len(lat_s) * 0.99))]

    # Same probe with the host->device transfer inside the timed region
    # (what a caller on THIS machine, through the tunnel, would see).
    cs4 = TpuConflictSet(config)
    lat_h = []
    for b in batches:
        t0 = time.perf_counter()
        out = cs4.resolve_packed(b)
        np.asarray(out.verdict)
        lat_h.append(time.perf_counter() - t0)
    lat_hs = sorted(lat_h[1:])
    p50_h = lat_hs[len(lat_hs) // 2]

    log(
        f"device: {dev_rate:,.0f} txn/s pipelined | kernel latency p50 "
        f"{p50*1e3:.0f}ms p99 {p99*1e3:.0f}ms | incl. host->device transfer "
        f"p50 {p50_h*1e3:.0f}ms | speedup {dev_rate / cpu_rate:.2f}x"
    )

    # ---- phase 5 (opt-in): small-batch latency sweep --------------------
    # BENCH_SMALL=1: the reference's resolver lives on a <3ms commit path
    # (performance.rst:49; Resolver.actor.cpp:174-208 latency histograms)
    # at batches of hundreds-to-thousands of txns. Measure that regime
    # honestly: device p50 (resident + transfer-inclusive) vs the CPU
    # backends on identical small batches. These numbers set the
    # RESOLVER_TPU_MIN_BATCH auto-routing knob (utils/knobs.py): below
    # the threshold the CPU resolves before the device dispatch returns.
    small = {}
    if os.environ.get("BENCH_SMALL"):
        for n_small in (512, 2048):
            cap_s = 4096
            cfg_s = KernelConfig(
                max_key_bytes=8, max_txns=cap_s, max_reads=cap_s,
                max_writes=cap_s, history_capacity=12 * cap_s,
                window_versions=window,
            )
            sb = [
                skiplist_style_batch(
                    rng, cfg_s, n_small, version=(i + 1) * version_step,
                    key_bytes=8, snapshot_lag=snapshot_lag,
                    keyspace=keyspace,
                )
                for i in range(12)
            ]
            css = TpuConflictSet(cfg_s)
            dev_sb = [jax.device_put(b.device_args()) for b in sb]
            jax.block_until_ready(dev_sb)
            lat_d, lat_t = [], []
            for db_, b in zip(dev_sb, sb):
                t0 = time.perf_counter()
                np.asarray(css.resolve_args(db_).verdict)
                lat_d.append(time.perf_counter() - t0)
            css2 = TpuConflictSet(cfg_s)
            for b in sb:
                t0 = time.perf_counter()
                np.asarray(css2.resolve_packed(b).verdict)
                lat_t.append(time.perf_counter() - t0)
            cpu_s = NativeSkipListConflictSet(window=window)
            lat_c = []
            for b in sb:
                (rk, ro, rt), (wk, wo, wt) = flat(b, "r"), flat(b, "w")
                t0 = time.perf_counter()
                cpu_s.resolve_raw(
                    int(b.version), b.snapshot[:n_small].astype(np.int64),
                    rk, ro, rt, wk, wo, wt,
                )
                lat_c.append(time.perf_counter() - t0)
            m_ = lambda xs: sorted(xs[1:])[len(xs[1:]) // 2]
            small[str(n_small)] = {
                "device_p50_ms": round(m_(lat_d) * 1e3, 2),
                "device_incl_transfer_p50_ms": round(m_(lat_t) * 1e3, 2),
                "cpu_skiplist_p50_ms": round(m_(lat_c) * 1e3, 2),
            }
            log(f"small-batch n={n_small}: {small[str(n_small)]}")

    suffix = "" if mode == "uniform" else f"_{mode}"
    print(
        json.dumps(
            {
                "metric": f"resolver_txns_per_sec_{n_txns // 1024}k_batch{suffix}",
                "value": round(dev_rate, 1),
                "unit": "txn/s",
                "vs_baseline": round(dev_rate / cpu_rate, 3),
                "baseline": cpu_name,
                "baseline_txns_per_sec": round(cpu_rate, 1),
                "reps": reps,
                "baseline_spread": [
                    round(min(cpu_samples[cpu_name]), 1),
                    round(max(cpu_samples[cpu_name]), 1),
                ],
                "device_spread": [
                    round(min(dev_samples), 1),
                    round(max(dev_samples), 1),
                ],
                "staging": "device",
                "fused_dispatch": fuse,
                "batches": n_batches,
                "p50_ms": round(p50 * 1e3, 1),
                "p99_ms": round(p99 * 1e3, 1),
                "p50_incl_transfer_ms": round(p50_h * 1e3, 1),
                "incl_transfer_pipelined_txn_s": round(incl_rate, 1),
                **({"small_batch": small} if small else {}),
            }
        )
    )


if __name__ == "__main__":
    main()
