#!/usr/bin/env python
"""Headline bench: resolver throughput at 64K-txn batches.

The TPU conflict kernel (foundationdb_tpu.ops.conflict.resolve_batch,
replacing fdbserver/SkipList.cpp detectConflicts) versus the measured CPU
baseline (foundationdb_tpu/native — the stand-in for the reference's
`fdbserver -r skiplisttest` microbench, fdbserver/SkipList.cpp:1082-1177:
uniform 1M keyspace, one read + one write range per txn).

Prints ONE JSON line:
  {"metric": ..., "value": txns/s on device, "unit": "txn/s",
   "vs_baseline": device_rate / cpu_baseline_rate}

Both sides resolve the identical batch stream, and their commit/abort
decisions are asserted identical before any timing is reported.

Env overrides: BENCH_TXNS (default 65536), BENCH_BATCHES (default 16),
BENCH_CPU_BATCHES (default 4).
"""

import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    n_txns = int(os.environ.get("BENCH_TXNS", 65536))
    n_batches = int(os.environ.get("BENCH_BATCHES", 16))
    cpu_batches = int(os.environ.get("BENCH_CPU_BATCHES", 4))
    keyspace = 1_000_000
    version_step = 200_000
    window = 1_000_000  # floor rises after 5 batches -> steady-state GC

    import jax

    from foundationdb_tpu.config import KernelConfig
    from foundationdb_tpu.models.conflict_set import TpuConflictSet
    from foundationdb_tpu.testing.benchgen import skiplist_style_batch

    log(f"devices: {jax.devices()}")
    cap = 1 << (n_txns - 1).bit_length()
    config = KernelConfig(
        max_key_bytes=8,
        max_txns=cap,
        max_reads=cap,
        max_writes=cap,
        history_capacity=8 * cap,  # ~window/version_step batches of writes
        fresh_slots=8,
        fresh_capacity=2 * cap,
        window_versions=window,
    )

    rng = np.random.default_rng(0)
    batches = []
    for i in range(n_batches):
        version = (i + 1) * version_step
        batches.append(
            skiplist_style_batch(
                rng, config, n_txns, version=version, keyspace=keyspace,
                key_bytes=8,
            )
        )
    log(f"generated {n_batches} batches of {n_txns} txns")

    # ---- CPU baseline (native C++ ConflictBatch-equivalent) -------------
    from foundationdb_tpu.native import NativeConflictSet

    def flat(batch, which):
        begin = batch.read_begin if which == "r" else batch.write_begin
        end = batch.read_end if which == "r" else batch.write_end
        txn = batch.read_txn if which == "r" else batch.write_txn
        n = batch.n_reads if which == "r" else batch.n_writes
        w = (begin.shape[1] - 1) * 4
        # interleave begin_i, end_i into one byte blob
        kb = np.frombuffer(begin[:n, :-1].astype(">u4").tobytes(), np.uint8)
        ke = np.frombuffer(end[:n, :-1].astype(">u4").tobytes(), np.uint8)
        blob = np.stack([kb.reshape(n, w), ke.reshape(n, w)], axis=1).reshape(-1)
        off = np.arange(2 * n + 1, dtype=np.int64) * w
        return blob, off, txn[:n].astype(np.int32)

    cpu = NativeConflictSet(window=window)
    cpu_times = []
    cpu_verdicts = []
    for i in range(cpu_batches):
        b = batches[i]
        rkeys, roff, rtxn = flat(b, "r")
        wkeys, woff, wtxn = flat(b, "w")
        snaps = b.snapshot[:n_txns].astype(np.int64)
        t0 = time.perf_counter()
        v = cpu.resolve_raw(
            int(b.version), snaps, rkeys, roff, rtxn, wkeys, woff, wtxn
        )
        cpu_times.append(time.perf_counter() - t0)
        cpu_verdicts.append(v)
    cpu_rate = n_txns * len(cpu_times) / sum(cpu_times)
    log(f"cpu baseline: {cpu_rate:,.0f} txn/s "
        f"(per-batch {[f'{t*1e3:.1f}ms' for t in cpu_times]})")

    # ---- TPU kernel ------------------------------------------------------
    cs = TpuConflictSet(config)
    # Warmup/compile on batch 0's shapes (all batches share shapes).
    t0 = time.perf_counter()
    out = cs.resolve_packed(batches[0])
    out.verdict.block_until_ready()
    log(f"first call (compile+run): {time.perf_counter() - t0:.1f}s")

    # Decision parity vs. the CPU baseline on the first batches.
    dev_v = np.asarray(out.verdict)[:n_txns]
    assert (dev_v == cpu_verdicts[0]).all(), "decision mismatch vs CPU baseline"

    dev_times = []
    for i in range(1, n_batches):
        b = batches[i]
        t0 = time.perf_counter()
        out = cs.resolve_packed(b)
        out.verdict.block_until_ready()
        dev_times.append(time.perf_counter() - t0)
        if i < cpu_batches:
            dv = np.asarray(out.verdict)[:n_txns]
            assert (dv == cpu_verdicts[i]).all(), f"mismatch at batch {i}"
    log("decision parity: OK")

    dev_rate = n_txns * len(dev_times) / sum(dev_times)
    lat = sorted(dev_times)
    p50 = lat[len(lat) // 2]
    p99 = lat[min(len(lat) - 1, int(len(lat) * 0.99))]
    log(
        f"device: {dev_rate:,.0f} txn/s | batch p50 {p50*1e3:.1f}ms "
        f"p99 {p99*1e3:.1f}ms | speedup {dev_rate / cpu_rate:.2f}x"
    )

    print(
        json.dumps(
            {
                "metric": f"resolver_txns_per_sec_{n_txns // 1024}k_batch",
                "value": round(dev_rate, 1),
                "unit": "txn/s",
                "vs_baseline": round(dev_rate / cpu_rate, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
